"""Observability: events, logging, TensorBoard, experiment tracking.

The reference wires three decoupled consumers onto one producer — stdlib
logging summaries, TinyDB metric persistence, TensorBoard scalars
(``examples/tinysys/main.py:49-58``) — so the trainer never knows its
observers. This package ships those consumers as framework components, plus
the canonical training events they consume.

Hot-path rule (SURVEY.md §7.3): every payload on the bus is already a
materialized host value — consumers never touch device arrays, so one epoch
has exactly one device→host sync per phase (``metrics.compute()``).
"""

from tpusystem.observe.events import (AnomalyDetected, BackoffApplied,
                                      CapacityArbitrated, Iterated,
                                      JobAdmitted, JobHalted, JobPreempted,
                                      RecoveryTimeline, ReplicaDiverged,
                                      RequestAdmitted, RequestCompleted,
                                      RequestEvicted, RolledBack,
                                      ServeStepped, StepTimed, Trained,
                                      Validated, WorkerExited,
                                      WorkerRelaunched)
from tpusystem.observe.flight import FlightRecorder
from tpusystem.observe.ledger import EventLedger, LedgerDivergence
from tpusystem.observe.logs import logging_consumer
from tpusystem.observe.metrics import (Histogram, ServeLatency,
                                       serve_metrics_consumer)
# the trace MODULE must import before profile's trace FUNCTION: importing
# a submodule binds it as a package attribute, and the later function
# import deliberately wins — `observe.trace` stays the device-profiler
# context manager it has always been. Span tracing is reached as
# `observe.Tracer` (preferred) or `from tpusystem.observe.trace import
# ...`; NOT via attribute access on the package (`import
# tpusystem.observe.trace; tpusystem.observe.trace.Tracer` resolves the
# shadowing function and fails — the price of keeping the old name).
from tpusystem.observe.trace import Span, TraceContext, Tracer
from tpusystem.observe.profile import (ProfilerBusy, StepTimer, annotate,
                                       step_span, trace)
from tpusystem.observe.tensorboard import SummaryWriter, tensorboard_consumer
from tpusystem.observe.tracking import (
    checkpoint_consumer, experiment, metrics_store, models_store,
    modules_store, iterations_store, repository, tracking_consumer,
)

__all__ = [
    'Trained', 'Validated', 'Iterated', 'StepTimed',
    'AnomalyDetected', 'BackoffApplied', 'RolledBack', 'ReplicaDiverged',
    'WorkerExited', 'WorkerRelaunched', 'RecoveryTimeline',
    'RequestAdmitted', 'RequestEvicted', 'RequestCompleted', 'ServeStepped',
    'JobAdmitted', 'JobPreempted', 'JobHalted', 'CapacityArbitrated',
    'logging_consumer', 'SummaryWriter', 'tensorboard_consumer',
    'tracking_consumer', 'checkpoint_consumer', 'experiment',
    'metrics_store', 'models_store',
    'modules_store', 'iterations_store', 'repository',
    'EventLedger', 'LedgerDivergence', 'StepTimer', 'annotate', 'step_span',
    'trace', 'ProfilerBusy',
    'Tracer', 'Span', 'TraceContext',
    'Histogram', 'ServeLatency', 'serve_metrics_consumer',
    'FlightRecorder',
]
