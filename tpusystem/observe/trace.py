"""Request-scoped tracing: one causally-linked timeline across the fleet.

The event plane (:mod:`tpusystem.observe.events`) narrates *that* things
happened and the metric plane (:mod:`tpusystem.observe.metrics`) says *how
often and how slow* — this module is the third plane: *what happened to
THIS request / THIS recovery, in order, across processes*. After the
serving fleet PRs a single request can cross a router, a replica, a
journal replay, and a reroute onto a different engine; a recovery crosses
detect → relaunch → restore → first-step on a supervisor. No scalar chart
can show that journey; a trace can.

Design rules, inherited from the rest of the framework:

* **Injectable clock** — the :class:`~tpusystem.serve.Scheduler`
  discipline: every timestamp comes from ``clock`` so tier-1 drills run
  on fake clocks with zero real sleeps.
* **Off by default, zero cost off** — every instrumented subsystem takes
  ``tracer=None`` and guards with one ``is not None`` check; a disabled
  tracer adds no per-tick host sync and no allocation (the
  ``trace_overhead`` bench row pins the budget).
* **Causal identity travels with the work** — a :class:`TraceContext`
  ``(trace_id, parent span id)`` rides the :class:`~tpusystem.serve.
  Request` itself, so the journal packs it for free and a replayed or
  rerouted row on a *different* engine parents to the original
  submission's trace. One request = ONE connected trace, kills or not.
* **Chrome trace-event export** — :meth:`Tracer.export` writes the
  `Trace Event Format` JSON that Perfetto (https://ui.perfetto.dev) and
  ``chrome://tracing`` open directly: one process row per host/replica
  (``process`` label → pid), spans as complete (``"ph": "X"``) events,
  the trace/parent ids in ``args`` so tooling and tests can walk the
  causal chain.
* **Cross-host collection rides the blob plane** — :meth:`Tracer.
  send_spans` ships a packed span set over the existing
  ``send_blob``/``fetch_blob`` wire at phase cadence (key
  ``trace:{process}``); :meth:`Tracer.accept_blob` is a chainable
  receiver and :meth:`Tracer.merge` folds any packed set in, so rank 0
  exports one JSON file showing the whole fleet.

Spans are tiny host-side records (name, ids, two floats, a small args
dict) — never device arrays; recording happens at lifecycle edges
(submit/admit/complete, recovery stages), never per token.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import pathlib
import pickle
import threading
import time
from typing import Any, Callable, Iterator

__all__ = ['TraceContext', 'Span', 'Tracer', 'connected_traces']


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The causal identity a unit of work carries: which trace it belongs
    to and which span fathered it. Frozen and picklable on purpose — it
    rides :class:`~tpusystem.serve.Request` through the journal's
    ``pack()``/``unpack()`` and across process boundaries unchanged, so
    a replayed row still knows its original submission."""

    trace_id: str
    parent: str | None = None        # span id of the parent span


@dataclasses.dataclass
class Span:
    """One named interval on one process row. ``end`` is None while the
    span is open (a request mid-decode, a recovery mid-restore); an open
    span still exports — with the tracer's *now* as its provisional end
    and ``"open": true`` in args — so a post-mortem trace shows work the
    process died holding."""

    name: str
    cat: str
    span_id: str
    trace_id: str
    parent: str | None
    process: str
    start: float
    end: float | None = None
    args: dict = dataclasses.field(default_factory=dict)
    phase: str = 'span'              # 'span' | 'instant'

    @property
    def context(self) -> TraceContext:
        """The context CHILDREN of this span should carry."""
        return TraceContext(trace_id=self.trace_id, parent=self.span_id)


class Tracer:
    """Span recorder for one process (host, replica, router, supervisor).

    Args:
        process: the process-row label in the exported trace
            (``'router'``, ``'rep0'``, ``'rank1'``...). Span and trace
            ids are namespaced by it, so merged fleets cannot collide.
        clock: wall-time source (``time.monotonic``); injectable so the
            fleet drills trace on their fake clocks. All tracers merged
            into one export must share a time base.
        sink: optional callable invoked with every *finished* span — the
            flight recorder's hook (:meth:`tpusystem.observe.flight.
            FlightRecorder.watch`).

    Thread-safe: spans arrive from scheduler loops, supervisor threads
    and blob receivers; a lock guards the span list and id counter.
    """

    def __init__(self, process: str = 'proc', *,
                 clock: Callable[[], float] = time.monotonic,
                 sink: Callable[[Span], None] | None = None) -> None:
        self.process = process
        self.clock = clock
        self.sink = sink
        self._lock = threading.Lock()
        self._seq = 0
        self._spans: dict[str, Span] = {}        # span_id -> Span (ordered)

    # ------------------------------------------------------------- record

    def _next_id(self, kind: str) -> str:
        with self._lock:
            self._seq += 1
            return f'{self.process}/{kind}{self._seq}'

    def context(self) -> TraceContext:
        """A fresh root context (new trace, no parent) — for work that
        starts here."""
        return TraceContext(trace_id=self._next_id('t'))

    def begin(self, name: str, *, cat: str = 'span',
              trace: TraceContext | None = None,
              args: dict | None = None) -> Span:
        """Open a span. With ``trace=None`` it roots a new trace; pass a
        :class:`TraceContext` to parent it into an existing one. Close
        with :meth:`end` (spans here are lifecycle intervals — submit to
        admit, admit to complete — not lexical blocks; use :meth:`span`
        for the lexical case)."""
        span_id = self._next_id('s')
        if trace is None:
            trace = self.context()
        span = Span(name=name, cat=cat, span_id=span_id,
                    trace_id=trace.trace_id, parent=trace.parent,
                    process=self.process, start=self.clock(),
                    args=dict(args or {}))
        with self._lock:
            self._spans[span_id] = span
        return span

    def end(self, span: Span | None, **args: Any) -> Span | None:
        """Close a span (idempotent; extra ``args`` merge in). Tolerates
        None so call sites can ``tracer.end(open_spans.pop(id, None))``."""
        if span is None or span.end is not None:
            return span
        span.end = self.clock()
        if args:
            span.args.update(args)
        if self.sink is not None:
            self.sink(span)
        return span

    @contextlib.contextmanager
    def span(self, name: str, *, cat: str = 'span',
             trace: TraceContext | None = None,
             args: dict | None = None) -> Iterator[Span]:
        """Lexical span: ``with tracer.span('checkpoint-save'): ...``."""
        opened = self.begin(name, cat=cat, trace=trace, args=args)
        try:
            yield opened
        finally:
            self.end(opened)

    def instant(self, name: str, *, cat: str = 'span',
                trace: TraceContext | None = None,
                args: dict | None = None) -> Span:
        """A zero-duration mark (a reroute decision, a health verdict)."""
        span = self.begin(name, cat=cat, trace=trace, args=args)
        span.end = span.start
        span.phase = 'instant'
        if self.sink is not None:
            self.sink(span)
        return span

    def record(self, name: str, start: float, end: float, *,
               cat: str = 'span', trace: TraceContext | None = None,
               args: dict | None = None) -> Span:
        """A span with explicit timestamps — how the supervisor's
        recovery timeline and the elastic coordinator's wave stages
        (already measured as clock offsets) become spans after the fact,
        subsuming the ad-hoc ``stages`` dicts of ``RecoveryTimeline`` /
        ``ElasticTimeline``."""
        span = self.begin(name, cat=cat, trace=trace, args=args)
        span.start, span.end = float(start), float(end)
        if self.sink is not None:
            self.sink(span)
        return span

    # ----------------------------------------------------------- collect

    def pack(self) -> bytes:
        """The span set as bytes for the blob plane (whole set each time
        — phase cadence, not per span; :meth:`merge` dedupes by id)."""
        with self._lock:
            spans = [dataclasses.asdict(span)
                     for span in self._spans.values()]
        return pickle.dumps((self.process, spans),
                            protocol=pickle.HIGHEST_PROTOCOL)

    def merge(self, source: 'Tracer | bytes') -> int:
        """Fold another tracer's spans (or a :meth:`pack`ed set) into
        this one; id-keyed, so re-sending at phase cadence is idempotent
        (later copies win — they may carry the closed end of a span that
        was open last push). Returns how many spans the source held."""
        if isinstance(source, Tracer):
            packed = source.pack()
        else:
            packed = bytes(source)
        _, spans = pickle.loads(packed)
        with self._lock:
            for payload in spans:
                span = Span(**payload)
                self._spans[span.span_id] = span
        return len(spans)

    def send_spans(self, transport: Any, to: int = 0) -> None:
        """Ship this process's spans to ``to``'s collector over the
        existing blob plane (``send_blob``, key ``trace:{process}``) —
        call at phase cadence, exactly like hot-state replication. The
        receiving side chains :meth:`accept_blob` into its transport's
        ``on_blob`` (the supervisor's blob receiver ignores non-
        ``replica:`` keys, so the two coexist)."""
        transport.send_blob(to, f'trace:{self.process}', self.pack())

    def accept_blob(self, sender: int, key: str, data: bytes) -> bool:
        """Blob-plane receiver: merge ``trace:*`` payloads, ignore
        everything else (returns whether the key was ours, so callers
        can chain receivers)."""
        if not key.startswith('trace:'):
            return False
        self.merge(data)
        return True

    # ------------------------------------------------------------ export

    def events(self) -> list[dict]:
        """The Chrome trace events (the ``traceEvents`` array): metadata
        rows first (one pid per process label), then every span as a
        complete (``X``) or instant (``i``) event with
        ``trace_id``/``span_id``/``parent`` in ``args``."""
        with self._lock:
            spans = list(self._spans.values())
        processes = sorted({span.process for span in spans})
        pids = {process: index + 1 for index, process in enumerate(processes)}
        now = self.clock()
        out: list[dict] = [
            {'ph': 'M', 'name': 'process_name', 'pid': pids[process],
             'tid': 0, 'args': {'name': process}}
            for process in processes]
        for span in spans:
            args = {'trace_id': span.trace_id, 'span_id': span.span_id,
                    **span.args}
            if span.parent is not None:
                args['parent'] = span.parent
            event = {'name': span.name, 'cat': span.cat,
                     'pid': pids[span.process], 'tid': 0,
                     'ts': span.start * 1e6, 'args': args}
            if span.phase == 'instant':
                event.update(ph='i', s='p')
            else:
                end = span.end
                if end is None:       # died holding it: provisional end
                    end = max(now, span.start)
                    args['open'] = True
                event.update(ph='X', dur=max(0.0, (end - span.start) * 1e6))
            out.append(event)
        return out

    def export(self, path: str | os.PathLike) -> pathlib.Path:
        """Write the Perfetto/``chrome://tracing``-openable JSON file."""
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        payload = {'traceEvents': self.events(), 'displayTimeUnit': 'ms'}
        tmp = path.with_name(path.name + '.tmp')
        tmp.write_text(json.dumps(payload))
        tmp.replace(path)            # atomic: a reader never sees a torn file
        return path

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


def connected_traces(events: list) -> dict:
    """Group exported span/instant events by ``trace_id`` and verify
    connectivity: every span's ``parent`` must resolve to a span in the
    SAME trace (the no-orphans invariant — a replayed or rerouted span
    whose parent was never collected would dangle here). Raises
    :exc:`ValueError` naming the orphans; returns
    ``{trace_id: [event, ...]}``. The shared validator behind the fleet
    chaos drills and the dryrun stage — and the check to run on any
    export before trusting it."""
    spans = [event for event in events if event.get('ph') in ('X', 'i')]
    by_trace: dict = {}
    for event in spans:
        by_trace.setdefault(event['args']['trace_id'], []).append(event)
    for trace_id, group in by_trace.items():
        span_ids = {event['args']['span_id'] for event in group}
        orphans = [event['args']['span_id'] for event in group
                   if event['args'].get('parent')
                   and event['args']['parent'] not in span_ids]
        if orphans:
            raise ValueError(
                f'trace {trace_id!r} has {len(orphans)} orphan span(s) '
                f'{orphans} — their parents were never collected; merge '
                f'every process\'s spans before validating')
    return by_trace
