"""Canonical training events.

The reference defines these in the application layer
(``examples/tinysys/tinysys/services/training.py:50-63``); they are the
ubiquitous language of every consumer, so the framework ships them. Payloads
carry the *aggregate* (host-side object with ``id``/``epoch``/``phase``) and
already-materialized metric floats — never device arrays.
"""

from __future__ import annotations

from typing import Any

from tpusystem.services.prodcon import event


@event
class Trained:
    """A training phase completed for the epoch."""
    model: Any
    metrics: dict[str, float]


@event
class Validated:
    """An evaluation phase completed for the epoch."""
    model: Any
    metrics: dict[str, float]


@event
class Iterated:
    """A full epoch (train + validate) completed."""
    model: Any
    loaders: Any = None


@event
class StepTimed:
    """Wall-clock timing of a span of steps (profiling consumer food)."""
    model: Any
    phase: str
    steps: int
    seconds: float

    @property
    def steps_per_second(self) -> float:
        return self.steps / self.seconds if self.seconds else 0.0


@event
class RecsysEvaluated:
    """The streaming recommender evaluator finished a phase-cadence pass
    over its held-out loader (:class:`tpusystem.recsys.RecsysEvaluator`
    via ``evaluation_consumer``); ``metrics`` carries materialized
    floats — ``auc``/``loss`` for click models, ``recall@k`` for
    retrieval models."""
    model: Any
    metrics: dict[str, float]


# --------------------------------------------------------------------------
# sentinel events — every rung of the divergence-escalation ladder
# (tpusystem.train.sentinel) is a domain event, so the hash-chain ledger
# and TensorBoard witness each transition exactly like any other
# occurrence. ``model`` is the host-side aggregate or the identity string.


@event
class AnomalyDetected:
    """A step's update was suppressed in-graph (non-finite loss/grads, or a
    grad-norm spike past the guard's z-score threshold)."""
    model: Any
    step: int
    kind: str          # 'nonfinite' | 'spike'
    loss: float
    gnorm: float
    zscore: float


@event
class BackoffApplied:
    """The sentinel changed the update scale (level 0 / scale 1.0 is the
    recovery back to full rate after a healthy streak)."""
    model: Any
    step: int
    level: int
    scale: float


@event
class RolledBack:
    """The sentinel rolled the state back to a committed checkpoint and
    skipped the offending cursor window (PaLM-style skip-batches)."""
    model: Any
    step: int
    to_step: int
    window: Any        # {'from': cursor, 'to': cursor} — the skipped range


@event
class ReplicaDiverged:
    """The cross-replica parity check flagged silently corrupted replicas
    (SDC) before they reached a checkpoint."""
    model: Any
    step: int | None
    replicas: list
    leaves: list


# --------------------------------------------------------------------------
# serving events — the continuous-batching engine's request lifecycle
# (tpusystem.serve): every admission, eviction and completion is a domain
# event on the bus, so the ledger orders a serving incident and
# TensorBoard charts queue depth / time-to-first-token / tokens-per-sec
# without the engine knowing its observers.


@event
class RequestAdmitted:
    """A queued request was prefilled and seated in an engine row;
    ``ttft`` is submit -> first token (time-to-first-token), seconds."""
    id: str
    row: int
    prompt_tokens: int
    ttft: float
    queue_depth: int


@event
class RequestEvicted:
    """A request left its row before finishing (``reason`` =
    ``'cancelled'``); ``produced`` tokens were emitted by then."""
    id: str
    produced: int
    reason: str


@event
class RequestCompleted:
    """A request finished (``reason`` = ``'length'`` | ``'stop'``) and
    its row/blocks returned to the free lists."""
    id: str
    produced: int
    reason: str
    seconds: float


@event
class RequestExpired:
    """A request's ``deadline`` passed before it finished; ``where`` says
    whether it was still ``'queued'`` (never seated — the starvation
    case under saturation) or ``'active'`` (evicted mid-decode);
    ``produced`` tokens were emitted by then."""
    id: str
    where: str
    produced: int
    waited: float


@event
class ServeStepped:
    """One scheduler iteration: current batch occupancy and queue depth,
    plus the sliding tokens-per-second the engine is sustaining.
    ``sampled`` is how many seated rows decode with ``temperature > 0``
    (the sampled-traffic gauge; 0 = all-greedy)."""
    step: int
    active: int
    queue_depth: int
    emitted: int
    tokens_per_sec: float
    sampled: int = 0


@event
class TokenStreamed:
    """One token delivered incrementally to a streaming consumer
    (:meth:`tpusystem.serve.InferenceService.submit` with ``on_token``):
    ``index`` is the token's position in the request's stream (0 = the
    first token, whose latency IS the admission's ``ttft``). Fires per
    token of streaming requests only — non-streaming traffic keeps its
    per-step ``ServeStepped.emitted`` aggregate."""
    id: str
    index: int
    token: int


@event
class LoadShed:
    """Admission control shed a queued request past the high watermark
    (:class:`tpusystem.serve.Watermarks`): ``slack`` is the seconds it
    had left before its deadline when shed (negative = already past,
    None = no deadline — shed last, newest first). Active rows are never
    shed."""
    id: str
    produced: int
    queue_depth: int
    slack: float | None


@event
class Backpressure:
    """The scheduler crossed its queue watermarks: ``engaged`` True past
    the high mark (upstream should route elsewhere), False once the
    backlog drained back to the low mark."""
    engaged: bool
    queue_depth: int


@event
class RequestReplayed:
    """An engine relaunch re-queued a journaled request: ``prefix`` is
    how many already-emitted tokens replay re-prefills (``where='hot'``)
    before decode resumes; 0 / ``where='cold'`` is the re-submit of a
    request the journal only knew as queued. Greedy and seeded sampled
    decode are both deterministic (the sampling counter is a pure
    function of ``(seed, position)``), so either way the final
    completion is token-exact against an uninterrupted run."""
    id: str
    prefix: int
    where: str                       # 'hot' | 'cold'
    waited: float


@event
class ReplicaUnhealthy:
    """The fleet router's health verdict on one replica: its step or
    submit died (the SIGKILL signature), or its heartbeat went stale.
    The verdict is one-way — the router never routes there again;
    ``routed`` is how many in-flight requests must re-home onto the
    survivors (:mod:`tpusystem.serve.fleet`)."""
    name: str
    cause: str
    routed: int


@event
class RequestRerouted:
    """The router moved a request to a different replica: ``cause`` is
    ``'failover'`` (its replica died — journal handoff), ``'timeout'``
    (it overstayed the per-replica patience ladder) or ``'hedge'`` (a
    duplicate racing the straggler; first completion wins). ``where`` /
    ``prefix`` follow ``RequestReplayed``'s convention: a hot move
    re-prefills ``prefix`` already-emitted tokens on the target engine
    and resumes; greedy and seeded sampled decode alike keep the final
    completion token-exact across the move (hedged sampled duplicates
    emit the identical stream on both legs)."""
    id: str
    origin: str
    target: str
    where: str                       # 'hot' | 'cold'
    prefix: int
    cause: str                       # 'failover' | 'timeout' | 'hedge'


@event
class PrefillHandoff:
    """A disaggregated fleet moved one finished prefill's KV strips
    from the prefill tier to a decode replica: exported through
    ``Engine.export_prefill``, shipped over the blob plane under
    ``kv:{request}`` (digest-verified end to end), and seated through
    ``admit_prefilled``/``adopt_prefill``. ``tokens`` is the strip's
    coverage (prompt + any replayed prefix), ``bytes`` the payload's
    KV weight (:mod:`tpusystem.serve.disagg`)."""
    id: str
    origin: str                      # prefill replica
    target: str                      # decode replica
    tokens: int
    bytes: int


@event
class HandoffCorrupted:
    """A ``kv:{request}`` handoff failed its digest frame between the
    prefill tier and a decode seat (:class:`tpusystem.serve.disagg.
    HandoffCorrupt`): the payload is dropped and the router re-places
    the request cold (re-prefill from the journaled prompt+prefix), so
    the corruption costs latency, never tokens. Charted as the
    ``serve/handoff_corrupt`` counter — a silently-re-placing fleet is
    visible on the dashboard."""
    id: str
    origin: str                      # prefill replica that exported it
    target: str                      # decode replica that refused it


@event
class RoleMismatched:
    """A decode-carrying request (non-empty emitted prefix) was offered
    to a prefill-only replica (:class:`tpusystem.serve.disagg.
    RoleMismatch`): the placement is refused and retried on the decode
    tier. Charted as the ``serve/role_mismatch`` counter; a nonzero
    rate means the router's role map and the fleet disagree."""
    id: str
    replica: str
    prefix: int


@event
class RouterTakeover:
    """A (re)started router rebuilt the fleet's authoritative state:
    ``source`` says where it came back from — ``'journal'`` (the
    router journal on the memstore plane was readable: hot rebuild) or
    ``'sweep'`` (journal absent/corrupt: cold rebuild from a health
    sweep of the replicas' own journals). ``reseated`` routes kept
    streaming on the replica that already held them, ``replaced`` were
    re-placed (hot or cold), ``settled`` completions were recovered
    into the idempotency table (nothing double-completes), ``handoffs``
    in-flight KV payloads were re-queued for delivery."""
    term: int
    source: str                      # 'journal' | 'sweep'
    reseated: int
    replaced: int
    settled: int
    handoffs: int
    seconds: float


@event
class RouterDeposed:
    """A router observed a lease term higher than its own: a standby
    fenced it and took over. The deposed router must halt (exit
    ``ROUTER_FENCED_EXIT`` = 47, deliberately NOT restartable) rather
    than keep placing requests against the new term — the split-brain
    guard of the takeover protocol."""
    term: int
    observed: int


@event
class FleetResized:
    """The traffic-driven autoscaler changed the replica set: sustained
    backpressure ``'grow'``\\ s it through the provision seam (capacity
    carved from training via the supervisor/elastic resize path),
    sustained idleness ``'shrink'``\\ s it back. ``replicas`` is the
    healthy fleet size AFTER the change."""
    action: str                      # 'grow' | 'shrink'
    replicas: int
    cause: str
    name: str                        # the replica added / retired


@event
class EngineRestarted:
    """A serving replica rebuilt its engine and replayed its journal —
    ``cause`` is ``'relaunch'`` (a fresh process found a recoverable
    journal: the supervised-relaunch path) or ``'stalled'`` (the step
    watchdog fired in-process); ``seconds`` is rebuild + replay."""
    cause: str
    replayed: int
    resubmitted: int
    seconds: float


# --------------------------------------------------------------------------
# supervisor events — the recovery control loop
# (tpusystem.parallel.supervisor) narrates every worker exit, relaunch and
# recovery through the bus, so the ledger orders a whole incident and
# TensorBoard charts MTTR without any trainer code.


@event
class WorkerExited:
    """The supervised worker process ended; ``action`` is the contract
    verdict (``relaunch`` / ``done`` / ``halt`` / ``crash-loop`` /
    ``drain`` for a forwarded preemption), ``reason`` the human-readable
    cause (exit-code name or signal). ``postmortem`` is what the worker
    saw: the parsed flight-recorder dump
    (:class:`~tpusystem.observe.FlightRecorder`) the supervisor read
    back after the exit — its last entries are the worker's final ticks
    — or None when flight recording is off or the worker died before
    its first dump."""
    rank: int
    code: int
    action: str
    uptime: float
    reason: str | None = None
    postmortem: Any = None


@event
class WorkerRelaunched:
    """The supervisor is restarting the worker after a restartable exit
    (``backoff`` seconds of capped exponential backoff + jitter already
    slept)."""
    rank: int
    attempt: int
    restarts: int
    backoff: float


@event
class RecoveryTimeline:
    """One full recovery, detect → first-step: ``stages`` maps each
    breadcrumb (``relaunch``, ``restore``, ``first-step``, plus anything
    the worker marked) to seconds since detection, ``seconds`` is the
    whole MTTR, ``source`` where the state came back from
    (``hot``/``disk``)."""
    rank: int
    step: int | None
    source: str | None
    seconds: float
    stages: dict


# --------------------------------------------------------------------------
# elastic events — the membership-epoch protocol
# (tpusystem.parallel.elastic): every proposed and committed world resize
# is a domain event, so the ledger orders a preemption-wave incident and
# TensorBoard charts the world size and resize latency over time.


@event
class WorldResizeProposed:
    """A supervisor's settle window closed and it broadcast a membership
    proposal; ``cause`` is what opened the wave (``'loss'`` / ``'join'``
    / ``'both'``)."""
    rank: int
    epoch: int
    members: list
    cause: str


@event
class WorldResized:
    """The membership epoch committed: every proposed member echoed the
    same (epoch, members) proposal; workers restart under the new world
    spec. ``seconds`` is wave-open → commit."""
    epoch: int
    members: list
    size: int
    seconds: float


@event
class ElasticTimeline:
    """One full elastic resize, wave-open → training resumed at the new
    size: ``stages`` maps each breadcrumb (``propose``, ``commit``,
    ``restore``, plus anything the resuming side marked) to seconds
    since the wave opened; ``source`` is where the state came back from
    (``hot-reshard``/``disk``)."""
    epoch: int
    size: int
    step: int | None
    source: str | None
    seconds: float
    stages: dict


# --------------------------------------------------------------------------
# orchestrator events — the multi-tenant gang narrative
# (tpusystem.orchestrator): admissions, halts, and capacity arbitration
# between tenants sharing one physical mesh. Orchestrator dispatches ride
# the SHARED producer deliberately — they are fleet-of-jobs facts, not
# one tenant's business — while each event's ``job`` field names the
# tenant it concerns (and a tenant's own bus stamps `.tenant` on events
# it emits; tpusystem.orchestrator.namespace has the scoping rules).


@event
class JobAdmitted:
    """The orchestrator seated a job on its submesh: ``chips`` devices
    carved from the pool, under ``priority`` (larger wins capacity)."""
    job: str
    kind: str
    priority: int
    chips: int


@event
class JobPreempted:
    """Capacity arbitration shrank ``job`` by ``chips`` devices in
    favor of higher-priority tenant ``to`` — the
    ``Supervisor.resize()`` / exit-46 path, so the shrunk job resumes
    token-exact on its smaller submesh and the move is a recorded debt
    the ebb pays back."""
    job: str
    chips: int
    to: str


@event
class JobHalted:
    """A tenant exited outside ``RESTART_EXITS`` and was halted —
    devices freed, nothing else touched (the blast-radius contract).
    ``reason`` is the typed verdict for ``code``
    (docs/multihost.md#restart-exit-code-table)."""
    job: str
    code: int
    reason: str


@event
class CapacityArbitrated:
    """One completed (two-phase-journaled) arbitration: a ``'grant'``
    moved ``chips`` devices toward ``requester`` (from the free pool
    and/or ``donor``), a ``'release'`` paid them back on ebb.
    ``seconds`` is decide → both sides re-ganged."""
    kind: str
    requester: str
    donor: str | None
    chips: int
    seconds: float
