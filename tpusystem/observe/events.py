"""Canonical training events.

The reference defines these in the application layer
(``examples/tinysys/tinysys/services/training.py:50-63``); they are the
ubiquitous language of every consumer, so the framework ships them. Payloads
carry the *aggregate* (host-side object with ``id``/``epoch``/``phase``) and
already-materialized metric floats — never device arrays.
"""

from __future__ import annotations

from typing import Any

from tpusystem.services.prodcon import event


@event
class Trained:
    """A training phase completed for the epoch."""
    model: Any
    metrics: dict[str, float]


@event
class Validated:
    """An evaluation phase completed for the epoch."""
    model: Any
    metrics: dict[str, float]


@event
class Iterated:
    """A full epoch (train + validate) completed."""
    model: Any
    loaders: Any = None


@event
class StepTimed:
    """Wall-clock timing of a span of steps (profiling consumer food)."""
    model: Any
    phase: str
    steps: int
    seconds: float

    @property
    def steps_per_second(self) -> float:
        return self.steps / self.seconds if self.seconds else 0.0
