"""Mergeable latency histograms: percentiles without a sorted array.

The serving and recovery layers have narrated latency as mean-only
scalars (``serve/ttft_seconds`` charts each admission; ``tok_s`` is a
sliding mean) — useless for a tail-latency claim. This module is the
metric plane done the way production serving systems do it (HDR
histogram style):

* **log-bucketed** — bucket boundaries grow geometrically
  (``floor * (1 + resolution) ** k``), so a fixed bucket count covers
  microseconds to minutes at a bounded *relative* error: any percentile
  read is within one bucket's relative resolution of the exact
  sorted-array answer (pinned by test).
* **exact counts, mergeable in any order** — a histogram is a counter
  per bucket; merging is counter addition, which is commutative and
  associative, so per-host histograms folded in ANY host order yield
  identical percentiles (pinned by test) — the property that makes
  fleet-wide p99 from per-replica shards correct by construction.
* **tiny on the wire** — :meth:`Histogram.state` is a dict of ints, so
  per-host shards ride the event/blob plane at phase cadence without
  shipping samples.

:func:`serve_metrics_consumer` feeds the three headline distributions —
TTFT, per-token decode seconds, recovery seconds — from the events the
serving/fleet/supervisor layers already dispatch, and charts
p50/p95/p99 to TensorBoard. ``bench.py`` prints the same percentiles as
the ``serve_ttft_p50_p99`` row.
"""

from __future__ import annotations

import math
from typing import Any, Iterable

from tpusystem.observe.events import (EngineRestarted, RecoveryTimeline,
                                      RequestAdmitted, RequestCompleted)
from tpusystem.services.prodcon import Consumer, Depends

__all__ = ['Histogram', 'ServeLatency', 'serve_metrics_consumer']


class Histogram:
    """Log-bucketed latency histogram with exact counts.

    Args:
        resolution: relative bucket width — a percentile read is within
            this fraction of the exact sorted-array answer (default 5%).
        floor: values at or below it share bucket 0 (absolute precision
            floor; latencies under a microsecond are all "instant").

    ``add``/``merge``/``percentile`` are the whole surface; ``state()``/
    ``from_state()`` round-trip the counters for the wire.
    """

    def __init__(self, resolution: float = 0.05,
                 floor: float = 1e-6) -> None:
        if not 0.0 < resolution < 1.0:
            raise ValueError(f'resolution must be in (0, 1), got {resolution}')
        if floor <= 0.0:
            raise ValueError(f'floor must be positive, got {floor}')
        self.resolution = resolution
        self.floor = floor
        self._log_growth = math.log1p(resolution)
        self.counts: dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None

    def _index(self, value: float) -> int:
        if value <= self.floor:
            return 0
        return 1 + int(math.log(value / self.floor) / self._log_growth)

    def _bounds(self, index: int) -> tuple[float, float]:
        if index <= 0:
            return (0.0, self.floor)
        growth = 1.0 + self.resolution
        return (self.floor * growth ** (index - 1),
                self.floor * growth ** index)

    def add(self, value: float, n: int = 1) -> None:
        value = float(value)
        index = self._index(value)
        self.counts[index] = self.counts.get(index, 0) + n
        self.count += n
        self.total += value * n
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    def merge(self, other: 'Histogram') -> 'Histogram':
        """Fold another histogram in (in place). Counter addition is
        commutative, so any merge order yields identical percentiles —
        the property the fleet aggregation relies on."""
        if (other.resolution != self.resolution
                or other.floor != self.floor):
            raise ValueError(
                f'histograms must share bucketing to merge: '
                f'({self.resolution}, {self.floor}) vs '
                f'({other.resolution}, {other.floor})')
        for index, n in other.counts.items():
            self.counts[index] = self.counts.get(index, 0) + n
        self.count += other.count
        self.total += other.total
        for bound in ('min', 'max'):
            theirs = getattr(other, bound)
            if theirs is not None:
                mine = getattr(self, bound)
                fold = min if bound == 'min' else max
                setattr(self, bound,
                        theirs if mine is None else fold(mine, theirs))
        return self

    @classmethod
    def merged(cls, shards: Iterable['Histogram']) -> 'Histogram':
        """A fresh histogram folding every shard (order-independent)."""
        out: Histogram | None = None
        for shard in shards:
            if out is None:
                out = cls(shard.resolution, shard.floor)
            out.merge(shard)
        return out if out is not None else cls()

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile (``q`` in [0, 1]) to within one
        bucket's relative resolution: the geometric midpoint of the
        bucket holding the rank, clamped to the observed min/max so a
        one-sample histogram reads back its sample."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f'q must be in [0, 1], got {q}')
        if not self.count:
            raise ValueError('empty histogram has no percentiles')
        rank = max(1, math.ceil(q * self.count))
        seen = 0
        for index in sorted(self.counts):
            seen += self.counts[index]
            if seen >= rank:
                low, high = self._bounds(index)
                mid = math.sqrt(max(low, self.floor * 1e-3) * high) \
                    if index > 0 else 0.0
                return min(max(mid, self.min), self.max)
        return self.max                                   # unreachable

    def summary(self) -> dict:
        """The headline row: count, mean, p50/p95/p99, max."""
        if not self.count:
            return {'count': 0}
        return {'count': self.count,
                'mean': self.total / self.count,
                'p50': self.percentile(0.50),
                'p95': self.percentile(0.95),
                'p99': self.percentile(0.99),
                'max': self.max}

    # ------------------------------------------------------------- wire

    def state(self) -> dict:
        """JSON-able counters for the wire (phase-cadence shipping)."""
        return {'resolution': self.resolution, 'floor': self.floor,
                'counts': dict(self.counts), 'count': self.count,
                'total': self.total, 'min': self.min, 'max': self.max}

    @classmethod
    def from_state(cls, state: dict) -> 'Histogram':
        out = cls(state['resolution'], state['floor'])
        out.counts = {int(index): int(n)
                      for index, n in state['counts'].items()}
        out.count = int(state['count'])
        out.total = float(state['total'])
        out.min = state['min']
        out.max = state['max']
        return out


class ServeLatency:
    """The three serving latency distributions, fed from bus events.

    * ``ttft`` — submit → first token, from ``RequestAdmitted.ttft``;
    * ``per_token`` — whole-life seconds over produced tokens, from
      ``RequestCompleted`` (the delivered-latency a user feels);
    * ``recovery`` — engine rebuild + replay / detect → first-step, from
      ``EngineRestarted`` and ``RecoveryTimeline``.

    Attach with :meth:`consumer` (chartless) or through
    :func:`serve_metrics_consumer` (charted). Per-host instances merge
    with ``Histogram.merge`` for the fleet-wide view.
    """

    def __init__(self, resolution: float = 0.05) -> None:
        self.ttft = Histogram(resolution)
        self.per_token = Histogram(resolution)
        self.recovery = Histogram(resolution)

    def observe(self, event: Any) -> None:
        if isinstance(event, RequestAdmitted):
            self.ttft.add(event.ttft)
        elif isinstance(event, RequestCompleted):
            if event.produced:
                self.per_token.add(event.seconds / event.produced)
        elif isinstance(event, EngineRestarted):
            self.recovery.add(event.seconds)
        elif isinstance(event, RecoveryTimeline):
            self.recovery.add(event.seconds)


def serve_metrics_consumer(latency: ServeLatency | None = None,
                           cadence: int = 16) -> Consumer:
    """Consumer charting the latency percentiles to TensorBoard.

    Every ``cadence`` admissions it charts ``serve/ttft_p50|p95|p99``
    and ``serve/token_seconds_p50|p99`` against the admission counter
    (requests have no global step — the tensorboard.py convention);
    recovery percentiles chart per restart (rare events). The writer
    enters through the same :func:`tpusystem.observe.tensorboard.writer`
    dependency seam as every other chart. Pass ``latency`` to share the
    histograms with a bench/report path.
    """
    from tpusystem.observe.tensorboard import SummaryWriter, writer
    consumer = Consumer('serve-metrics')
    state = latency or ServeLatency()
    admits = [0]
    restarts = [0]

    @consumer.handler
    def on_admitted(event: RequestAdmitted,
                    board: SummaryWriter = Depends(writer)) -> None:
        state.observe(event)
        admits[0] += 1
        if admits[0] % cadence:
            return
        for q, tag in ((0.50, 'p50'), (0.95, 'p95'), (0.99, 'p99')):
            board.add_scalar(f'serve/ttft_{tag}',
                             state.ttft.percentile(q), admits[0])
        if state.per_token.count:
            board.add_scalar('serve/token_seconds_p50',
                             state.per_token.percentile(0.50), admits[0])
            board.add_scalar('serve/token_seconds_p99',
                             state.per_token.percentile(0.99), admits[0])

    @consumer.handler
    def on_completed(event: RequestCompleted) -> None:
        state.observe(event)

    @consumer.handler
    def on_recovery(event: EngineRestarted | RecoveryTimeline,
                    board: SummaryWriter = Depends(writer)) -> None:
        state.observe(event)
        restarts[0] += 1
        board.add_scalar('serve/recovery_p50',
                         state.recovery.percentile(0.50), restarts[0])
        board.add_scalar('serve/recovery_p99',
                         state.recovery.percentile(0.99), restarts[0])

    return consumer
