"""Logging consumer — epoch/phase summaries via stdlib logging.

Reference parity: ``examples/tinysys/tinysys/services/logging.py:16-32``.
"""

from __future__ import annotations

import logging

from tpusystem.observe.events import Iterated, StepTimed, Trained, Validated
from tpusystem.services.prodcon import Consumer


def logging_consumer(logger: logging.Logger | None = None) -> Consumer:
    """Consumer printing one summary line per phase/epoch/timing event."""
    log = logger or logging.getLogger('tpusystem')
    consumer = Consumer('logging')

    def describe(metrics: dict[str, float]) -> str:
        return ', '.join(f'{name}: {value:.4f}' for name, value in metrics.items())

    @consumer.handler
    def on_trained(event: Trained) -> None:
        log.info('epoch %s train      | %s',
                 getattr(event.model, 'epoch', '?'), describe(event.metrics))

    @consumer.handler
    def on_validated(event: Validated) -> None:
        log.info('epoch %s evaluation | %s',
                 getattr(event.model, 'epoch', '?'), describe(event.metrics))

    @consumer.handler
    def on_iterated(event: Iterated) -> None:
        log.info('epoch %s done       | model %s',
                 getattr(event.model, 'epoch', '?'), event.model.id)

    @consumer.handler
    def on_timed(event: StepTimed) -> None:
        log.info('epoch %s %s: %.1f steps/s (%d steps in %.2fs)',
                 getattr(event.model, 'epoch', '?'), event.phase,
                 event.steps_per_second, event.steps, event.seconds)

    return consumer
