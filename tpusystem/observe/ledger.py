"""Event-stream hash chain: the distributed race detector.

The reference is single-threaded by construction — synchronous bus dispatch
(``torchsystem/services/prodcon.py:209-218``) means event ordering can never
race. On a pod, every host runs its own bus, and SPMD correctness silently
assumes all hosts observe *the same event stream in the same order*: a host
that skips an epoch event, dispatches in a different order, or diverges in a
payload will eventually desynchronize collectives or storage. There is no
TSAN for this; the debug-mode mechanism SURVEY.md §5 prescribes is a
**hash chain of dispatched events compared across hosts**.

Usage::

    ledger = EventLedger()
    ledger.tap(producer)                   # observe every dispatch
    ...
    ledger.verify(transport)               # epoch boundary; raises on divergence

Chain entries hash the event's *type name* and its **stable** payload fields
(ints, strings, bools, None). Floats are excluded by default — metric values
legitimately differ across hosts before the cross-host reduce, and the
detector targets *structural* divergence (ordering, missing/extra events,
shape-of-payload drift), not numeric noise. Pass ``strict=True`` to include
floats (rounded) when the stream is expected to be numerically identical.
"""

from __future__ import annotations

import dataclasses
from hashlib import sha256
from typing import Any

from tpusystem.services.prodcon import Producer


class LedgerDivergence(AssertionError):
    """Hosts dispatched different event streams."""


class EventLedger:
    """Order-sensitive digest of every event dispatched on a bus."""

    def __init__(self, strict: bool = False) -> None:
        self.strict = strict
        self.digest = sha256(b'genesis').hexdigest()
        self.count = 0

    def _stable_fields(self, message: Any) -> list[tuple[str, Any]]:
        if not dataclasses.is_dataclass(message):
            return []
        stable: list[tuple[str, Any]] = []
        for field in dataclasses.fields(message):
            value = getattr(message, field.name, None)
            if isinstance(value, (int, str, bool, type(None))):
                stable.append((field.name, value))
            elif self.strict and isinstance(value, float):
                stable.append((field.name, round(value, 6)))
        return stable

    def record(self, message: Any) -> str:
        """Fold one event into the chain; returns the new chain digest."""
        entry = (type(message).__name__, self._stable_fields(message))
        self.digest = sha256((self.digest + repr(entry)).encode()).hexdigest()
        self.count += 1
        return self.digest

    def tap(self, producer: Producer) -> 'EventLedger':
        """Attach to a producer so every dispatch is recorded."""
        producer.taps.append(self.record)
        return self

    def verify(self, transport: Any) -> str:
        """Gather (count, digest) from every host and require unanimity.

        Call at a safe point (epoch boundary, checkpoint commit). Raises
        :class:`LedgerDivergence` naming the disagreeing ranks; returns the
        agreed digest otherwise. On :class:`~tpusystem.parallel.multihost.
        Loopback` this is a no-op self-check.
        """
        states = sorted(transport.gather(
            (getattr(transport, 'rank', 0), self.count, self.digest)))
        if len({(count, digest) for _, count, digest in states}) > 1:
            detail = ', '.join(
                f'rank{rank}: {count} events, {digest[:12]}…'
                for rank, count, digest in states)
            raise LedgerDivergence(
                f'event streams diverged across hosts ({detail}) — a host '
                f'dispatched a different event sequence; check for '
                f'host-dependent control flow in services/consumers')
        return self.digest
