"""Typed configuration layer over the Registry.

The reference configures in plain Python at the composition root with DI
overrides as the late-binding seam, and motivates its ``Registry`` as the
hook for config-file-driven construction (``torchsystem/registry/
accessors.py:195-231``, ``docs/registry.md`` "load a model from a
configuration file") — but ships no config subsystem (SURVEY.md §5). This
module supplies it, keeping code-as-config primary:

- :func:`load` — read a JSON or TOML file into a plain dict;
- :func:`build` — resolve a ``{'name': ..., 'arguments': {...}}`` spec to a
  registered class and construct it, recursively for nested specs. The spec
  schema is **exactly** the registry's captured-argument schema
  (:func:`tpusystem.registry.core.describe_value`), so configs and identity
  metadata are one format;
- :func:`snapshot` — the inverse: serialize a constructed, registered
  object back to a buildable spec. ``build(snapshot(model), registry)``
  reconstructs an equivalent model, and both share one identity hash — the
  reproducibility contract.

Nested-spec resolution rule: inside ``arguments``, a dict with exactly the
keys ``{'name', 'arguments'}`` is a sub-spec; a bare string that names a
registered type with a zero-argument constructor is an argless sub-spec
(the collapsed form the registry emits). Any other value passes through
verbatim.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any

from tpusystem.registry import Registry, getarguments, getname


def load(path: str | pathlib.Path) -> dict:
    """Read a config file (``.json`` or ``.toml``) into a dict."""
    path = pathlib.Path(path)
    if path.suffix == '.toml':
        try:
            import tomllib            # stdlib from 3.11
        except ModuleNotFoundError:
            import tomli as tomllib   # the API-identical 3.10 backport
        return tomllib.loads(path.read_text())
    return json.loads(path.read_text())


def _is_spec(value: Any) -> bool:
    return isinstance(value, dict) and set(value) == {'name', 'arguments'}


def _resolve(value: Any, registry: Registry) -> Any:
    if _is_spec(value):
        return build(value, registry)
    if isinstance(value, str) and registry.get(value) is not None:
        signature = registry.signature(value)
        if not signature:  # argless constructor: the collapsed capture form
            return build({'name': value, 'arguments': {}}, registry)
    if isinstance(value, list):
        return [_resolve(item, registry) for item in value]
    return value


def build(spec: dict | str, registry: Registry) -> Any:
    """Construct the object a spec describes, resolving names through the
    registry and recursing into nested specs.

    Raises:
        KeyError: when the spec names a type the registry doesn't know —
            the config and the code disagree, which must fail loudly.
    """
    if isinstance(spec, str):
        spec = {'name': spec, 'arguments': {}}
    name = spec['name']
    cls = registry.get(name)
    if cls is None:
        raise KeyError(
            f'config names unknown type {name!r}; registered: {registry.keys()}')
    arguments = {
        key: _resolve(value, registry)
        for key, value in spec.get('arguments', {}).items()
    }
    return cls(**arguments)


def snapshot(obj: Any) -> dict:
    """Serialize a registered object to a buildable spec (the inverse of
    :func:`build`). Requires the object's class to be registered so its
    constructor arguments were captured."""
    return {'name': getname(obj), 'arguments': getarguments(obj)}
