"""Grouped gather-matmul — Pallas TPU kernels for fused MoE dispatch/combine.

The megablocks insight (Gale et al., 2022) applied to this repo's MoE
decomposition: expert matmuls run at 0.806 MFU while dispatch/combine are
pure HBM row traffic the MXU idles through (BASELINE.md round-5 phase
table). These kernels make the data movement ride the matmuls instead of
preceding/following them:

* :func:`gather_rows_matmul` — the **dispatch direction**. For each expert
  the kernel walks that expert's seating indices (scalar-prefetched) and
  DMAs activation rows from the *unpermuted* token array — which never
  leaves HBM — straight into a VMEM tile that feeds the expert's matmul.
  The [experts*capacity, dim] dispatch buffer of the gather/scatter impls
  is never materialized: the standalone dispatch copy disappears into the
  first expert matmul's loads. Row gathers are double-buffered (tile c+1's
  rows stream in while tile c's hidden sweep runs on the MXU).

* :func:`matmul_scatter_rows` — the **combine direction** (and, with
  swapped operands, the transpose of the dispatch direction). A grouped
  matmul whose epilogue scatters each finished row — scaled by its combine
  weight — directly onto its token's output row via read-modify-write
  DMAs. The k-way weighted sum happens in the epilogue: no token-order
  gather pass ever reads the expert buffer back. TPU Pallas grids execute
  sequentially on a core and rows within one tile belong to one expert
  (distinct tokens), so the RMW accumulation is race-free by construction.

Both kernels take ``transpose_rhs`` so the backward pass *reuses the same
kernels with swapped operands* (d_buffer = gather-matmul of the output
cotangent against w2^T; d_tokens = matmul-scatter of the hidden cotangent
against w1^T) — the discipline the fused flash backward proved. MXU
accumulation is float32 throughout (``preferred_element_type``), rounded
once to the output dtype, matching the gather impl's numerics class.

Row indices use ``rows`` (the source/destination array length) as the
sentinel for empty slots / dropped assignments: gathered sentinel rows are
masked to zero through the per-row scale, scattered sentinel rows skip
their DMAs entirely. ``interpret=None`` auto-selects interpreter mode
off-TPU, so tier-1 CPU tests exercise the kernels' numerics directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusystem.ops.pallas import CompilerParams

LANES = 128   # lane tile; TPU block minor dims must be multiples
SUBLANES = 8  # sublane tile for f32
SCALE_LANES = 8   # trailing dim of the per-row scale input — a compact
                  # [rows] f32 vector is not Mosaic-lowerable (see
                  # flash.py's STATS note); 8 replicated lanes are.


def _auto_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        return jax.default_backend() not in ('tpu', 'axon')
    return interpret


def _pick_block(size: int, want: int, granule: int) -> int | None:
    """Largest divisor of ``size`` that is <= ``want`` and a multiple of
    ``granule`` (``granule=1`` in interpret mode — the interpreter has no
    tiling constraints, so tiny test shapes still block)."""
    want = min(want, size)
    best = None
    for candidate in range(granule, want + 1, granule):
        if size % candidate == 0:
            best = candidate
    return best


def _blocks(rows_per_group: int, inner: int, interpret: bool,
            want_rows: int, want_inner: int, dtype):
    # sublane tile grows as elements shrink: (8, 128) f32, (16, 128) bf16
    sublanes = SUBLANES * 4 // max(1, jnp.dtype(dtype).itemsize)
    granule = 1 if interpret else sublanes
    inner_granule = 1 if interpret else LANES
    block_rows = _pick_block(rows_per_group, want_rows, granule)
    block_inner = _pick_block(inner, want_inner, inner_granule)
    if block_rows is None or block_inner is None:
        raise ValueError(
            f'grouped_matmul cannot tile rows_per_group={rows_per_group}, '
            f'inner={inner} on TPU (need multiples of {granule}/'
            f'{inner_granule}); pad the capacity/hidden dims or use '
            "sparse_impl='gather'")
    return block_rows, block_inner


def _scale_input(scale: jax.Array) -> jax.Array:
    """[rows] f32 -> [rows, SCALE_LANES] replicated (Mosaic-tileable)."""
    return jnp.tile(scale.astype(jnp.float32)[:, None], (1, SCALE_LANES))


def _gather_matmul_kernel(row_ref, src_any, rhs_ref, scale_ref, out_ref,
                          x_scr, sem, *, block_rows: int, tiles: int,
                          transpose_rhs: bool):
    """Grid (groups, row_tiles, n_tiles), n innermost. At n == 0 the row
    tile's source rows are DMA'd from HBM into the double-buffered VMEM
    scratch — tile t+1's rows are issued right after tile t's wait, so the
    gather streams behind the n-sweep's matmuls."""
    group, row_tile, n_idx = (pl.program_id(0), pl.program_id(1),
                              pl.program_id(2))
    row_tiles = pl.num_programs(1)
    tile = group * row_tiles + row_tile

    def for_each_row(t, action):
        def body(i, _):
            row = row_ref[t * block_rows + i]
            copy = pltpu.make_async_copy(src_any.at[row],
                                         x_scr.at[t % 2, i], sem.at[t % 2])
            action(copy)
            return 0
        jax.lax.fori_loop(0, block_rows, body, 0)

    @pl.when(n_idx == 0)
    def _gather():
        @pl.when(tile == 0)
        def _prologue():
            for_each_row(0, lambda copy: copy.start())
        for_each_row(tile, lambda copy: copy.wait())

        @pl.when(tile + 1 < tiles)
        def _stream_next():
            for_each_row(tile + 1, lambda copy: copy.start())

    gathered = x_scr[tile % 2]
    # per-row scale in the compute dtype: zero for empty slots (masking the
    # stale/clamped gather), the combine weight on the backward reuse —
    # the same multiply the gather impl's custom_vjp pair applies
    scaled = gathered * scale_ref[:, :1].astype(gathered.dtype)
    contract = (((1,), (1,)), ((), ())) if transpose_rhs \
        else (((1,), (0,)), ((), ()))
    out_ref[...] = jax.lax.dot_general(
        scaled, rhs_ref[0], contract,
        preferred_element_type=jnp.float32).astype(out_ref.dtype)


def gather_rows_matmul(src, rhs, row_ids, row_scale, *,
                       rows_per_group: int, transpose_rhs: bool = False,
                       out_dtype=None, block_rows: int = 512,
                       block_cols: int = 512,
                       interpret: bool | None = None):
    """Fused gather + grouped matmul: ``out[j] = (row_scale[j] *
    src[row_ids[j]]) @ rhs[j // rows_per_group]``.

    Args:
        src: [n, k] token array — stays in HBM; rows are DMA'd on demand.
        rhs: [groups, k, m] stacked weights ([groups, m, k] with
            ``transpose_rhs``, contracted over the trailing dim — the
            backward reuse never materializes a transposed weight copy).
        row_ids: [groups * rows_per_group] int32 source row per output
            row, pre-clamped to [0, n); masked by ``row_scale`` instead
            of bounds-checked.
        row_scale: [groups * rows_per_group] float per-row factor — 0/1
            seat validity on the dispatch direction, the combine weight
            on the d_buffer backward direction (applied in the compute
            dtype, matching the gather impl).
        rows_per_group: static rows per group (= expert capacity).

    Returns [groups * rows_per_group, m] in ``out_dtype`` (default:
    ``src.dtype``), accumulated in float32 on the MXU.
    """
    interpret = _auto_interpret(interpret)
    groups = rhs.shape[0]
    contract_dim = rhs.shape[2] if transpose_rhs else rhs.shape[1]
    out_cols = rhs.shape[1] if transpose_rhs else rhs.shape[2]
    if src.shape[1] != contract_dim:
        raise ValueError(f'src cols {src.shape[1]} != rhs contract dim '
                         f'{contract_dim}')
    out_dtype = out_dtype or src.dtype
    block_rows, block_cols = _blocks(rows_per_group, out_cols, interpret,
                                     block_rows, block_cols, src.dtype)
    row_tiles = rows_per_group // block_rows
    tiles = groups * row_tiles

    if transpose_rhs:
        rhs_spec = pl.BlockSpec((1, block_cols, contract_dim),
                                lambda g, r, n, ids: (g, n, 0))
    else:
        rhs_spec = pl.BlockSpec((1, contract_dim, block_cols),
                                lambda g, r, n, ids: (g, 0, n))
    kernel = functools.partial(
        _gather_matmul_kernel, block_rows=block_rows, tiles=tiles,
        transpose_rhs=transpose_rhs)
    flops = 2 * groups * rows_per_group * contract_dim * out_cols
    bytes_accessed = (src.size * src.dtype.itemsize
                      + rhs.size * rhs.dtype.itemsize
                      + groups * rows_per_group * out_cols
                      * jnp.dtype(out_dtype).itemsize)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(groups, row_tiles, out_cols // block_cols),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                rhs_spec,
                pl.BlockSpec((block_rows, SCALE_LANES),
                             lambda g, r, n, ids: (g * row_tiles + r, 0)),
            ],
            out_specs=pl.BlockSpec(
                (block_rows, block_cols),
                lambda g, r, n, ids: (g * row_tiles + r, n)),
            scratch_shapes=[
                pltpu.VMEM((2, block_rows, contract_dim), src.dtype),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            (groups * rows_per_group, out_cols), out_dtype),
        compiler_params=CompilerParams(
            dimension_semantics=('arbitrary', 'arbitrary', 'arbitrary')),
        cost_estimate=pl.CostEstimate(flops=flops,
                                      bytes_accessed=bytes_accessed,
                                      transcendentals=0),
        interpret=interpret,
    )(row_ids, src, rhs, _scale_input(row_scale))


def _matmul_scatter_kernel(row_ref, lhs_ref, rhs_ref, bias_ref, scale_ref,
                           init_ref, out_any, rows_ref, acc, rd_scr, wr_scr,
                           sem, *, block_rows: int, tokens: int,
                           transpose_rhs: bool, save_rows: bool):
    """Grid (groups, row_tiles, k_tiles), k innermost: f32 accumulation
    over the contraction sweep; the epilogue on the last k step adds the
    bias, optionally stores the plain row block (the residual the MoE
    backward needs), then RMWs each weighted row onto its token's output
    row. Reads are batched (issue all, wait all), the merged tile is one
    vector op, writes are batched; sentinel rows skip their DMAs. The
    sequential TPU grid plus distinct tokens within a tile (one expert
    seats a token at most once) make the RMW exact."""
    del init_ref
    group, row_tile, k_idx = (pl.program_id(0), pl.program_id(1),
                              pl.program_id(2))
    k_steps = pl.num_programs(2)
    base = (group * pl.num_programs(1) + row_tile) * block_rows

    @pl.when(k_idx == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    contract = (((1,), (1,)), ((), ())) if transpose_rhs \
        else (((1,), (0,)), ((), ()))
    acc[...] += jax.lax.dot_general(
        lhs_ref[...], rhs_ref[0], contract,
        preferred_element_type=jnp.float32)

    @pl.when(k_idx == k_steps - 1)
    def _epilogue():
        tile = acc[...]
        if bias_ref is not None:
            tile = tile + bias_ref[0].astype(jnp.float32)
        finished = tile.astype(wr_scr.dtype)
        if save_rows:
            rows_ref[...] = finished

        def for_each_row(action):
            def body(i, _):
                token = row_ref[base + i]

                @pl.when(token < tokens)   # sentinel rows move nothing
                def _valid():
                    action(i, token)
                return 0
            jax.lax.fori_loop(0, block_rows, body, 0)

        def read(i, token):
            pltpu.make_async_copy(out_any.at[token], rd_scr.at[i],
                                  sem).start()

        def read_wait(i, token):
            pltpu.make_async_copy(out_any.at[token], rd_scr.at[i],
                                  sem).wait()

        for_each_row(read)
        for_each_row(read_wait)
        # the k-way weighted combine IS this add: each of a token's seated
        # choices lands here once, in the compute dtype like the gather
        # impl's weighted sum
        weighted = finished * scale_ref[:, :1].astype(finished.dtype)
        wr_scr[...] = rd_scr[...] + weighted

        def write(i, token):
            pltpu.make_async_copy(wr_scr.at[i], out_any.at[token],
                                  sem).start()

        def write_wait(i, token):
            pltpu.make_async_copy(wr_scr.at[i], out_any.at[token],
                                  sem).wait()

        for_each_row(write)
        for_each_row(write_wait)


def matmul_scatter_rows(lhs, rhs, bias, row_ids, row_scale, tokens: int, *,
                        rows_per_group: int, transpose_rhs: bool = False,
                        out_dtype=None, save_rows: bool = True,
                        block_rows: int = 512, block_k: int = 512,
                        interpret: bool | None = None):
    """Fused grouped matmul + scatter-combine: computes ``row[j] =
    lhs[j] @ rhs[j // rows_per_group] (+ bias)`` and accumulates
    ``out[row_ids[j]] += row_scale[j] * row[j]`` in the epilogue.

    Args:
        lhs: [groups * rows_per_group, k] expert-major buffer rows.
        rhs: [groups, k, m] stacked weights ([groups, m, k] with
            ``transpose_rhs``).
        bias: [groups, m] per-group bias added before the scatter, or
            ``None`` (the backward reuse has no bias).
        row_ids: [groups * rows_per_group] int32 destination token per
            row; ``tokens`` is the sentinel for empty slots / dropped
            assignments — their DMAs are skipped entirely.
        row_scale: [groups * rows_per_group] float combine weight (0 for
            empty slots; 1s on the backward reuse).
        tokens: number of output rows.
        save_rows: also return the plain (unweighted, biased) rows —
            the residual the MoE backward needs for d_weights/d_w2; the
            backward reuse passes False and skips that HBM write.

    Returns ``(out [tokens, m], rows [groups*rows_per_group, m] | None)``.
    """
    interpret = _auto_interpret(interpret)
    groups = rhs.shape[0]
    contract_dim = rhs.shape[2] if transpose_rhs else rhs.shape[1]
    out_cols = rhs.shape[1] if transpose_rhs else rhs.shape[2]
    if lhs.shape[1] != contract_dim:
        raise ValueError(f'lhs cols {lhs.shape[1]} != rhs contract dim '
                         f'{contract_dim}')
    out_dtype = out_dtype or lhs.dtype
    block_rows, block_k = _blocks(rows_per_group, contract_dim, interpret,
                                  block_rows, block_k, lhs.dtype)
    row_tiles = rows_per_group // block_rows

    if transpose_rhs:
        rhs_spec = pl.BlockSpec((1, out_cols, block_k),
                                lambda g, r, k, ids: (g, 0, k))
    else:
        rhs_spec = pl.BlockSpec((1, block_k, out_cols),
                                lambda g, r, k, ids: (g, k, 0))
    row_block = pl.BlockSpec(
        (block_rows, out_cols),
        lambda g, r, k, ids: (g * row_tiles + r, 0))
    in_specs = [
        pl.BlockSpec((block_rows, block_k),
                     lambda g, r, k, ids: (g * row_tiles + r, k)),
        rhs_spec,
    ]
    operands = [lhs, rhs]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, out_cols),
                                     lambda g, r, k, ids: (g, 0)))
        operands.append(bias)
    in_specs.append(pl.BlockSpec((block_rows, SCALE_LANES),
                                 lambda g, r, k, ids:
                                 (g * row_tiles + r, 0)))
    operands.append(_scale_input(row_scale))
    in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))   # zero init
    operands.append(jnp.zeros((tokens, out_cols), out_dtype))

    out_shape = [jax.ShapeDtypeStruct((tokens, out_cols), out_dtype)]
    out_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    if save_rows:
        out_shape.append(jax.ShapeDtypeStruct(
            (groups * rows_per_group, out_cols), out_dtype))
        out_specs.append(row_block)

    def kernel(row_ref, lhs_ref, rhs_ref, *rest):
        if bias is not None:
            bias_ref, rest = rest[0], rest[1:]
        else:
            bias_ref = None
        scale_ref, init_ref, out_ref = rest[0], rest[1], rest[2]
        rest = rest[3:]
        rows_ref = rest[0] if save_rows else None
        scratch = rest[1:] if save_rows else rest
        return _matmul_scatter_kernel(
            row_ref, lhs_ref, rhs_ref, bias_ref, scale_ref, init_ref,
            out_ref, rows_ref, *scratch, block_rows=block_rows,
            tokens=tokens, transpose_rhs=transpose_rhs,
            save_rows=save_rows)

    flops = 2 * groups * rows_per_group * contract_dim * out_cols
    bytes_accessed = (lhs.size * lhs.dtype.itemsize
                      + rhs.size * rhs.dtype.itemsize
                      + (1 + save_rows) * groups * rows_per_group * out_cols
                      * jnp.dtype(out_dtype).itemsize
                      + 2 * tokens * out_cols
                      * jnp.dtype(out_dtype).itemsize)
    # the prefetched ids are the LAST positional input index (bias/scale
    # shift it); the zeros init aliases output 0 so `out` needs no
    # in-kernel zeroing pass
    alias_index = 1 + len(operands) - 1   # ids + tensor operands, 0-based
    results = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(groups, row_tiles, contract_dim // block_k),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((block_rows, out_cols), jnp.float32),
                pltpu.VMEM((block_rows, out_cols), out_dtype),
                pltpu.VMEM((block_rows, out_cols), out_dtype),
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=out_shape,
        input_output_aliases={alias_index: 0},
        compiler_params=CompilerParams(
            dimension_semantics=('arbitrary', 'arbitrary', 'arbitrary')),
        cost_estimate=pl.CostEstimate(flops=flops,
                                      bytes_accessed=bytes_accessed,
                                      transcendentals=0),
        interpret=interpret,
    )(row_ids, *operands)
    if save_rows:
        return results[0], results[1]
    return results[0], None
