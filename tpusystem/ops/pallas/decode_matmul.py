"""Fused decode-chain matmuls — Pallas TPU kernels for the serving path.

One greedy-decode token-step at small batch is weight-streaming bound:
the ``[B, dim]`` activation is a few KB while every matrix param crosses
HBM once per step (benchmarks/decode_roofline.py: the 125M chain streams
~250 MB/step at f32). These kernels attack the two byte levers at once:

* :func:`decode_matmul` — one fused dequantize-matmul. The activation
  block is **resident in VMEM for the whole weight sweep** (its index
  map is constant) while the weight is streamed column-tile by
  column-tile through Pallas's double-buffered grid pipeline. With an
  int8/fp8 :class:`~tpusystem.ops.precision.QuantizedLeaf` the *narrow*
  values are the streamed operand — the tile is widened to the compute
  dtype in VMEM (a VPU convert that never touches HBM) and the
  per-output-channel scale multiplies the f32 accumulator once in the
  epilogue (the scale is a per-column constant, so it factors out of the
  contraction exactly). XLA cannot hoist a dequantized wide copy out of
  the decode loop here: the dequant lives inside an opaque kernel, which
  is what makes quantized streaming and fusion compose.

* :func:`decode_ffn` — the fc→gelu→proj **chain** in one kernel: the
  grid walks the hidden dimension; each step dequantizes one fc column
  tile, applies bias+activation to the ``[B, block_h]`` hidden slab
  while it is still in VMEM, and folds it into the proj contraction's
  f32 accumulator. The ``[B, 4*dim]`` hidden activation never exists in
  HBM, and both weight streams ride one grid.

Module discipline (flash/grouped_matmul): ``interpret=None``
auto-selects interpreter mode off-TPU so tier-1 CPU tests exercise the
kernel numerics directly; the shared ``CompilerParams`` alias; shapes
the TPU cannot tile fall back to the einsum path
(:func:`tpusystem.ops.precision.qdot` — also the parity reference),
pinned by the pure :func:`decode_plan`. Accumulation is float32
throughout (``preferred_element_type``), bias/activation applied to the
f32 accumulator and rounded once to the output dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusystem.ops.pallas import CompilerParams
from tpusystem.ops.precision import QuantizedLeaf, qdot

LANES = 128   # lane tile; TPU block minor dims must be multiples


def _auto_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        return jax.default_backend() not in ('tpu', 'axon')
    return interpret


def _pick_block(size: int, want: int, granule: int) -> int | None:
    """Largest divisor of ``size`` that is <= ``want`` and a multiple of
    ``granule`` (1 in interpret mode — no tiling constraints there)."""
    want = min(want, size)
    best = None
    for candidate in range(granule, want + 1, granule):
        if size % candidate == 0:
            best = candidate
    return best


def decode_plan(inner: int, out_cols: int, interpret: bool,
                want: int = 512) -> int | None:
    """Pure tiling decision for one streamed weight ``[inner, out_cols]``:
    the output-column block size, or ``None`` when the shape cannot tile
    on TPU (minor dims must divide into LANES multiples) — the caller
    then takes the einsum fallback. Pinned by tests so a jax upgrade
    cannot silently change which shapes run fused."""
    granule = 1 if interpret else LANES
    if not interpret and inner % granule:
        return None     # the weight tile's minor dim under transpose-free
        # streaming is out_cols; inner rides sublanes, which Mosaic pads —
        # but a non-lane-multiple inner also breaks the x block, so refuse
    return _pick_block(out_cols, want, granule)


def _split(w) -> tuple[jax.Array, jax.Array | None]:
    """(streamed operand, per-output-channel scale row or None)."""
    if isinstance(w, QuantizedLeaf):
        return w.values, w.scales.reshape(1, -1)
    return w, None


def _row(vec, cols: int) -> jax.Array:
    """[cols] -> [1, cols] f32 (a compact vector is not Mosaic-tileable;
    one replicated sublane row is — the grouped_matmul SCALE_LANES
    lesson, minor-dim flavored)."""
    return jnp.asarray(vec, jnp.float32).reshape(1, cols)


def _matmul_kernel(x_ref, w_ref, *rest, activation, have_scale, have_bias,
                   out_dtype):
    refs = list(rest)
    scale_ref = refs.pop(0) if have_scale else None
    bias_ref = refs.pop(0) if have_bias else None
    (out_ref,) = refs
    tile = w_ref[...].astype(x_ref.dtype)
    acc = jax.lax.dot_general(x_ref[...], tile, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    if scale_ref is not None:
        acc = acc * scale_ref[...]
    if bias_ref is not None:
        acc = acc + bias_ref[...]
    if activation is not None:
        acc = activation(acc)
    out_ref[...] = acc.astype(out_dtype)


def decode_matmul(x, w, bias=None, *, activation=None, out_dtype=None,
                  block_cols: int = 512, interpret: bool | None = None):
    """Fused ``activation(x @ dequant(w) + bias)`` with ``x`` VMEM-resident
    and ``w`` streamed in column tiles.

    Args:
        x: ``[B, K]`` activations (the compute dtype — bf16 on TPU).
        w: ``[K, N]`` weight, plain or a
            :class:`~tpusystem.ops.precision.QuantizedLeaf` (int8/fp8
            values + ``[1, N]`` scales dequantized in-kernel).
        bias: ``[N]`` or None; added to the f32 accumulator.
        activation: applied to the f32 accumulator (e.g. ``jax.nn.gelu``).

    Returns ``[B, N]`` in ``out_dtype`` (default ``x.dtype``). Falls back
    to the :func:`~tpusystem.ops.precision.qdot` einsum path when
    :func:`decode_plan` refuses the shape.
    """
    interpret = _auto_interpret(interpret)
    values, scales = _split(w)
    (batch, inner), (inner_w, out_cols) = x.shape, values.shape
    if inner != inner_w:
        raise ValueError(f'x cols {inner} != w rows {inner_w}')
    out_dtype = out_dtype or x.dtype
    block = decode_plan(inner, out_cols, interpret, block_cols)
    if block is None:       # einsum fallback — same math, XLA-tiled
        acc = qdot(x, w)
        if bias is not None:
            acc = acc + jnp.asarray(bias, jnp.float32)
        if activation is not None:
            acc = activation(acc)
        return acc.astype(out_dtype)

    in_specs = [
        pl.BlockSpec((batch, inner), lambda n: (0, 0)),     # resident
        pl.BlockSpec((inner, block), lambda n: (0, n)),     # streamed
    ]
    operands = [x, values]
    if scales is not None:
        in_specs.append(pl.BlockSpec((1, block), lambda n: (0, n)))
        operands.append(scales)
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, block), lambda n: (0, n)))
        operands.append(_row(bias, out_cols))
    kernel = functools.partial(
        _matmul_kernel, activation=activation, have_scale=scales is not None,
        have_bias=bias is not None, out_dtype=out_dtype)
    flops = 2 * batch * inner * out_cols
    bytes_accessed = (x.nbytes + values.nbytes
                      + (scales.nbytes if scales is not None else 0)
                      + batch * out_cols * jnp.dtype(out_dtype).itemsize)
    return pl.pallas_call(
        kernel,
        grid=(out_cols // block,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((batch, block), lambda n: (0, n)),
        out_shape=jax.ShapeDtypeStruct((batch, out_cols), out_dtype),
        compiler_params=CompilerParams(dimension_semantics=('arbitrary',)),
        cost_estimate=pl.CostEstimate(flops=flops,
                                      bytes_accessed=bytes_accessed,
                                      transcendentals=0),
        interpret=interpret,
    )(*operands)


def _ffn_kernel(x_ref, w1_ref, *rest, activation, have_s1, have_s2,
                out_dtype):
    refs = list(rest)
    s1_ref = refs.pop(0) if have_s1 else None
    b1_ref = refs.pop(0)
    w2_ref = refs.pop(0)
    s2_ref = refs.pop(0) if have_s2 else None
    b2_ref, out_ref, acc = refs
    step, steps = pl.program_id(0), pl.num_programs(0)

    @pl.when(step == 0)
    def _zero():
        acc[...] = jnp.zeros_like(acc)

    hidden = jax.lax.dot_general(
        x_ref[...], w1_ref[...].astype(x_ref.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if s1_ref is not None:      # per-hidden-channel scale BEFORE the
        hidden = hidden * s1_ref[...]   # nonlinearity — real values needed
    hidden = activation(hidden + b1_ref[...])
    acc[...] += jax.lax.dot_general(
        hidden.astype(x_ref.dtype), w2_ref[...].astype(x_ref.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(step == steps - 1)
    def _epilogue():
        total = acc[...]
        if s2_ref is not None:  # per-output scale factors out of the sum
            total = total * s2_ref[...]
        out_ref[...] = (total + b2_ref[...]).astype(out_dtype)


def decode_ffn(x, w1, b1, w2, b2, *, activation=jax.nn.gelu,
               out_dtype=None, block_hidden: int = 512,
               interpret: bool | None = None):
    """The fused FFN chain ``(activation(x @ dequant(w1) + b1)) @
    dequant(w2) + b2`` in one kernel: the grid walks the hidden
    dimension, so the ``[B, hidden]`` activation lives only as one
    ``[B, block_hidden]`` VMEM slab per step and both weight streams
    share one double-buffered pipeline. ``w1``/``w2`` may each be plain
    or quantized; ``w1``'s per-hidden-channel scale is applied per tile
    *before* the nonlinearity (the math needs real values there),
    ``w2``'s per-output scale once in the epilogue."""
    interpret = _auto_interpret(interpret)
    v1, s1 = _split(w1)
    v2, s2 = _split(w2)
    (batch, inner), (inner_w, hidden) = x.shape, v1.shape
    hidden_w, out_cols = v2.shape
    if inner != inner_w or hidden != hidden_w:
        raise ValueError(f'chain shapes do not compose: x {x.shape}, '
                         f'w1 {v1.shape}, w2 {v2.shape}')
    out_dtype = out_dtype or x.dtype
    # the hidden dim is the streamed/blocked one; the output width N must
    # itself be lane-tileable since the whole [B, N] accumulator is
    # resident
    block = decode_plan(inner, hidden, interpret, block_hidden)
    if block is None or (not interpret and out_cols % LANES):
        mid = qdot(x, w1)
        mid = activation(mid + jnp.asarray(b1, jnp.float32))
        acc = qdot(mid.astype(x.dtype), w2)
        return (acc + jnp.asarray(b2, jnp.float32)).astype(out_dtype)

    in_specs = [
        pl.BlockSpec((batch, inner), lambda h: (0, 0)),      # resident
        pl.BlockSpec((inner, block), lambda h: (0, h)),      # fc stream
    ]
    operands = [x, v1]
    if s1 is not None:
        in_specs.append(pl.BlockSpec((1, block), lambda h: (0, h)))
        operands.append(s1.reshape(1, hidden))
    in_specs.append(pl.BlockSpec((1, block), lambda h: (0, h)))
    operands.append(_row(b1, hidden))
    in_specs.append(pl.BlockSpec((block, out_cols), lambda h: (h, 0)))
    operands.append(v2)                                      # proj stream
    if s2 is not None:
        in_specs.append(pl.BlockSpec((1, out_cols), lambda h: (0, 0)))
        operands.append(s2.reshape(1, out_cols))
    in_specs.append(pl.BlockSpec((1, out_cols), lambda h: (0, 0)))
    operands.append(_row(b2, out_cols))

    kernel = functools.partial(
        _ffn_kernel, activation=activation, have_s1=s1 is not None,
        have_s2=s2 is not None, out_dtype=out_dtype)
    flops = 2 * batch * inner * hidden + 2 * batch * hidden * out_cols
    bytes_accessed = (x.nbytes + v1.nbytes + v2.nbytes
                      + batch * out_cols * jnp.dtype(out_dtype).itemsize)
    return pl.pallas_call(
        kernel,
        grid=(hidden // block,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((batch, out_cols), lambda h: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((batch, out_cols), out_dtype),
        scratch_shapes=[pltpu.VMEM((batch, out_cols), jnp.float32)],
        compiler_params=CompilerParams(dimension_semantics=('arbitrary',)),
        cost_estimate=pl.CostEstimate(flops=flops,
                                      bytes_accessed=bytes_accessed,
                                      transcendentals=batch * hidden),
        interpret=interpret,
    )(*operands)
