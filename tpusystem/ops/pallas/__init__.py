"""Hand-written Pallas TPU kernels (flash attention, grouped gather-matmul).

Shared compat: jax renamed ``TPUCompilerParams`` -> ``CompilerParams``
across releases; every kernel module takes the alias from here so the
fallback logic lives once.
"""

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, 'CompilerParams', None) \
    or pltpu.TPUCompilerParams

__all__ = ['CompilerParams']
