"""Row gather / row scatter-add — the shared Pallas kernel pair under
embedding lookup.

The fused-MoE kernels (:mod:`tpusystem.ops.pallas.grouped_matmul`) are
structurally embedding kernels already: ``gather_rows_matmul`` DMAs
scattered source rows into VMEM tiles (a lookup whose consumer happens to
be a matmul) and ``matmul_scatter_rows``'s epilogue read-modify-writes
finished rows onto arbitrary destination rows (a grad scatter whose
producer happens to be a matmul). This module hoists the *row movement*
halves into a standalone pair the recommender workload
(:mod:`tpusystem.recsys`) rides:

* :func:`gather_rows` — the **lookup direction**. The kernel walks a
  scalar-prefetched id list and DMAs table rows from HBM straight into a
  double-buffered VMEM scratch (tile t+1's rows stream in while tile t
  is scaled and stored), multiplies by a per-row scale (0 masks padded /
  foreign-shard ids, a pooling weight otherwise), and writes the block.
  The table never leaves HBM whole.

* :func:`scatter_add_rows` — the **grad direction** (the transpose of
  the gather). Each cotangent row is read-modify-written onto its
  table row in **float32**, strictly sequentially within a tile, so
  duplicate ids in one batch — the scatter-add collision case a Zipfian
  id distribution guarantees — accumulate exactly (TPU grids execute
  sequentially on a core, and each row's read completes before its
  write issues). Sentinel ids (``>= table rows``) skip their DMAs.

:func:`embedding_lookup` wraps the pair in a ``custom_vjp``: forward is
the scaled gather, backward scatter-adds the cotangents into a
zero-initialized f32 table (rounded once to the table dtype) and
re-gathers rows for the scale cotangent.

Fallback discipline (per :mod:`~tpusystem.ops.pallas.decode_matmul`,
adapted for a *training* hot path): the pure :func:`lookup_plan` pins
the ``jnp.take``/segment-sum fallback **off-TPU or on untileable
shapes** — unlike the decode kernels, lookups run inside every train
step, where an interpreter-mode kernel would be pure overhead, so
``impl='auto'`` never interprets. Explicit ``impl='fused'`` bypasses the
plan (``interpret=None`` still auto-selects interpreter mode off-TPU),
which is how tier-1 CPU tests drive the kernels' numerics directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusystem.ops.pallas import CompilerParams

LANES = 128   # lane tile; TPU block minor dims must be multiples
SUBLANES = 8  # sublane tile for f32
SCALE_LANES = 8   # trailing dim of the per-row scale input — a compact
                  # [rows] f32 vector is not Mosaic-lowerable (the
                  # grouped_matmul lesson); 8 replicated lanes are.


def _auto_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        return jax.default_backend() not in ('tpu', 'axon')
    return interpret


def _pick_block(size: int, want: int, granule: int) -> int | None:
    """Largest divisor of ``size`` that is <= ``want`` and a multiple of
    ``granule`` (1 in interpret mode — no tiling constraints there)."""
    want = min(want, size)
    best = None
    for candidate in range(granule, want + 1, granule):
        if size % candidate == 0:
            best = candidate
    return best


def lookup_plan(count: int, dim: int, dtype, interpret: bool,
                want_rows: int = 256) -> int | None:
    """Pure tiling decision for one ``[count]``-id lookup into a
    ``[*, dim]`` table: the id-block size, or ``None`` for the
    ``jnp.take``/segment-sum fallback.

    ``None`` in interpret mode (off-TPU) **by design**: the lookup sits
    in the training hot path, where an interpreted kernel per step is
    pure overhead — the decode kernels' auto-interpret discipline does
    not transfer. On TPU, ``None`` when the row minor dim cannot tile
    (``dim`` not a LANES multiple) or no id block divides ``count``.
    Pinned by tests so a jax upgrade cannot silently change which shapes
    run fused.
    """
    if interpret:
        return None
    if dim % LANES:
        return None
    granule = SUBLANES * 4 // max(1, jnp.dtype(dtype).itemsize)
    return _pick_block(count, want_rows, granule)


def _scale_input(scale: jax.Array) -> jax.Array:
    """[rows] f32 -> [rows, SCALE_LANES] replicated (Mosaic-tileable)."""
    return jnp.tile(jnp.asarray(scale, jnp.float32)[:, None],
                    (1, SCALE_LANES))


def _gather_kernel(id_ref, src_any, scale_ref, out_ref, scr, sem, *,
                   block_rows: int, tiles: int):
    """Grid (tiles,). Each tile's source rows are DMA'd from HBM into the
    double-buffered scratch — tile t+1's rows are issued right after tile
    t's wait, so the gather streams behind the scale-and-store."""
    tile = pl.program_id(0)

    def for_each_row(t, action):
        def body(i, _):
            row = id_ref[t * block_rows + i]
            copy = pltpu.make_async_copy(src_any.at[row],
                                         scr.at[t % 2, i], sem.at[t % 2])
            action(copy)
            return 0
        jax.lax.fori_loop(0, block_rows, body, 0)

    @pl.when(tile == 0)
    def _prologue():
        for_each_row(0, lambda copy: copy.start())
    for_each_row(tile, lambda copy: copy.wait())

    @pl.when(tile + 1 < tiles)
    def _stream_next():
        for_each_row(tile + 1, lambda copy: copy.start())

    # scale in f32 (0 masks padded/foreign ids), round once to out dtype —
    # the exact formula of the take fallback, so f32 parity is bitwise
    scaled = scr[tile % 2].astype(jnp.float32) * scale_ref[:, :1]
    out_ref[...] = scaled.astype(out_ref.dtype)


def gather_rows(src, row_ids, row_scale, *, block_rows: int = 256,
                out_dtype=None, interpret: bool | None = None):
    """Fused row gather: ``out[j] = row_scale[j] * src[row_ids[j]]``.

    Args:
        src: [rows, dim] table — stays in HBM; rows are DMA'd on demand.
        row_ids: [n] int32 source row per output row, pre-clamped to
            [0, rows); masked by ``row_scale`` instead of bounds-checked
            (the grouped_matmul contract).
        row_scale: [n] float per-row factor — 0 for padded / non-owned
            ids, 1 (or a pooling weight) otherwise; applied in f32.

    Returns [n, dim] in ``out_dtype`` (default ``src.dtype``).
    """
    interpret = _auto_interpret(interpret)
    count, dim = row_ids.shape[0], src.shape[1]
    out_dtype = out_dtype or src.dtype
    granule = 1 if interpret else (
        SUBLANES * 4 // max(1, jnp.dtype(src.dtype).itemsize))
    block = _pick_block(count, block_rows, granule)
    if block is None or (not interpret and dim % LANES):
        raise ValueError(
            f'gather_rows cannot tile n={count}, dim={dim} on TPU (need '
            f'id blocks in multiples of {granule}, dim a multiple of '
            f'{LANES}); use the jnp.take fallback (lookup_plan pins it)')
    tiles = count // block
    kernel = functools.partial(_gather_kernel, block_rows=block,
                               tiles=tiles)
    bytes_accessed = (count * dim * src.dtype.itemsize
                      + count * dim * jnp.dtype(out_dtype).itemsize)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec((block, SCALE_LANES), lambda t, ids: (t, 0)),
            ],
            out_specs=pl.BlockSpec((block, dim), lambda t, ids: (t, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, block, dim), src.dtype),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((count, dim), out_dtype),
        compiler_params=CompilerParams(dimension_semantics=('arbitrary',)),
        cost_estimate=pl.CostEstimate(flops=count * dim,
                                      bytes_accessed=bytes_accessed,
                                      transcendentals=0),
        interpret=interpret,
    )(jnp.asarray(row_ids, jnp.int32), src, _scale_input(row_scale))


def _scatter_add_kernel(id_ref, rows_ref, scale_ref, init_ref, out_any,
                        rd_scr, wr_scr, sem, *, block_rows: int,
                        table_rows: int):
    """Grid (tiles,). Strictly sequential per-row read-modify-write in
    f32: row i's read completes before its write issues, and row i+1's
    read issues only after row i's write completes — so duplicate ids
    within one tile (and across tiles: TPU grids are sequential on a
    core) accumulate exactly instead of losing collisions to a batched
    RMW. Sentinel rows (``>= table_rows``) skip their DMAs entirely."""
    del init_ref
    tile = pl.program_id(0)
    base = tile * block_rows

    def body(i, _):
        row = id_ref[base + i]

        @pl.when(row < table_rows)   # sentinel rows move nothing
        def _valid():
            read = pltpu.make_async_copy(out_any.at[row], rd_scr.at[0], sem)
            read.start()
            read.wait()
            contrib = (rows_ref[pl.ds(i, 1)].astype(jnp.float32)
                       * scale_ref[pl.ds(i, 1), :1])
            wr_scr[...] = rd_scr[...] + contrib
            write = pltpu.make_async_copy(wr_scr.at[0], out_any.at[row], sem)
            write.start()
            write.wait()
        return 0
    jax.lax.fori_loop(0, block_rows, body, 0)


def scatter_add_rows(rows, row_ids, row_scale, table_rows: int, *,
                     block_rows: int = 256,
                     interpret: bool | None = None):
    """Fused row scatter-add: ``out[row_ids[j]] += row_scale[j] * rows[j]``
    into a zero-initialized **float32** ``[table_rows, dim]`` table.

    ``table_rows`` is the sentinel id for padded / non-owned rows — their
    DMAs are skipped entirely. Accumulation is f32 regardless of the
    cotangent dtype (the grad-scatter contract); the caller rounds once
    to the table dtype. Duplicate ids accumulate exactly (see the kernel
    docstring) — the collision case the batched-RMW combine kernel in
    grouped_matmul never faces (one expert seats a token at most once)
    but an embedding grad under a Zipfian batch always does.
    """
    interpret = _auto_interpret(interpret)
    count, dim = rows.shape
    granule = 1 if interpret else (
        SUBLANES * 4 // max(1, jnp.dtype(rows.dtype).itemsize))
    block = _pick_block(count, block_rows, granule)
    if block is None or (not interpret and dim % LANES):
        raise ValueError(
            f'scatter_add_rows cannot tile n={count}, dim={dim} on TPU; '
            f'use the segment-sum fallback (lookup_plan pins it)')
    tiles = count // block
    kernel = functools.partial(_scatter_add_kernel, block_rows=block,
                               table_rows=table_rows)
    bytes_accessed = (rows.size * rows.dtype.itemsize
                      + 3 * count * dim * 4)      # read + write per row
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec((block, dim), lambda t, ids: (t, 0)),
                pl.BlockSpec((block, SCALE_LANES), lambda t, ids: (t, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),   # zeros init
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[
                pltpu.VMEM((1, dim), jnp.float32),
                pltpu.VMEM((1, dim), jnp.float32),
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((table_rows, dim), jnp.float32),
        # the zeros init aliases the output: no in-kernel zeroing pass
        input_output_aliases={3: 0},
        compiler_params=CompilerParams(dimension_semantics=('arbitrary',)),
        cost_estimate=pl.CostEstimate(flops=2 * count * dim,
                                      bytes_accessed=bytes_accessed,
                                      transcendentals=0),
        interpret=interpret,
    )(jnp.asarray(row_ids, jnp.int32), rows, _scale_input(row_scale),
      jnp.zeros((table_rows, dim), jnp.float32))


# ---------------------------------------------------------------------------
# the differentiable lookup built on the pair


def _take_lookup(table, clamped, scale):
    """Reference / fallback path: XLA gather + masking multiply. The
    transpose of ``jnp.take`` is XLA's scatter-add (the segment-sum), so
    autodiff supplies the grad scatter here. The f32 multiply mirrors the
    kernel's epilogue exactly — f32 forward parity is bitwise."""
    safe = jnp.minimum(clamped, table.shape[0] - 1)
    rows = jnp.take(table, safe, axis=0)
    return (rows.astype(jnp.float32) * scale[:, None]).astype(table.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_lookup(config, table, clamped, scale):
    block_rows, interpret = config
    return gather_rows(table, jnp.minimum(clamped, table.shape[0] - 1),
                       scale, block_rows=block_rows, interpret=interpret)


def _fused_lookup_fwd(config, table, clamped, scale):
    out = _fused_lookup(config, table, clamped, scale)
    return out, (table, clamped, scale)


def _fused_lookup_bwd(config, residuals, d_out):
    import numpy as np
    block_rows, interpret = config
    table, clamped, scale = residuals
    # cotangent scatter: out[j] = scale[j] * table[id_j]  =>
    # d_table[id_j] += scale[j] * d_out[j], f32 accumulation, rounded once.
    # Sentinel ids (== table rows) skip their DMAs — no grad for padding.
    d_table = scatter_add_rows(d_out, clamped, scale, table.shape[0],
                               block_rows=block_rows,
                               interpret=interpret).astype(table.dtype)
    # d_scale[j] = <table[id_j], d_out[j]> — one unscaled re-gather
    rows = gather_rows(table, jnp.minimum(clamped, table.shape[0] - 1),
                       jnp.ones_like(scale), block_rows=block_rows,
                       interpret=interpret)
    d_scale = jnp.sum(rows.astype(jnp.float32)
                      * d_out.astype(jnp.float32), axis=-1)
    # mask the sentinel rows' dots (their gather clamped to a REAL row)
    d_scale = jnp.where(clamped < table.shape[0], d_scale, 0.0)
    return (d_table, np.zeros(clamped.shape, jax.dtypes.float0), d_scale)


_fused_lookup.defvjp(_fused_lookup_fwd, _fused_lookup_bwd)


def embedding_lookup(table, ids, weights=None, *, impl: str = 'auto',
                     block_rows: int = 256,
                     interpret: bool | None = None):
    """Differentiable embedding lookup ``out[j] = w[j] * table[ids[j]]``.

    Ids outside ``[0, rows)`` (e.g. ``-1`` multi-hot padding) produce
    zero rows and contribute no gradient. ``weights`` (optional, [n])
    scales each row — a pooling weight; its gradient is the rowwise dot
    with the cotangent.

    ``impl``: ``'take'`` is the XLA gather path (autodiff supplies the
    segment-sum grad scatter), ``'fused'`` the Pallas pair above
    (``custom_vjp``: f32 scatter-add of cotangents), ``'auto'`` consults
    :func:`lookup_plan` — fused on TPU where the shape tiles, take
    otherwise (always take off-TPU: a per-step interpreted kernel is
    pure overhead; parity tests force ``impl='fused'``).
    """
    interpret = _auto_interpret(interpret)
    rows = table.shape[0]
    ids = jnp.asarray(ids, jnp.int32)
    valid = (ids >= 0) & (ids < rows)
    clamped = jnp.where(valid, ids, rows)     # sentinel == rows
    scale = valid.astype(jnp.float32)
    if weights is not None:
        scale = scale * jnp.asarray(weights, jnp.float32)
    if impl == 'auto':
        impl = 'fused' if lookup_plan(ids.shape[0], table.shape[1],
                                      table.dtype, interpret,
                                      block_rows) else 'take'
    if impl == 'take':
        return _take_lookup(table, clamped, scale)
    if impl != 'fused':
        raise ValueError(f'unknown impl {impl!r}; '
                         "expected 'auto', 'fused' or 'take'")
    return _fused_lookup((block_rows, interpret), table, clamped, scale)
