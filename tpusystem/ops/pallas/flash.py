"""Flash attention — Pallas TPU kernel.

Blockwise-online-softmax attention: O(seq) memory instead of the O(seq^2)
scores tensor that XLA attention materializes (the allocation that caps
single-chip GPT-2 batch size). Forward and backward are hand-written
kernels; the public entry :func:`flash_attention` carries a ``custom_vjp``
so ``jax.grad`` works transparently.

Kernel shape notes (see /opt/skills/guides/pallas_guide.md):
* grid iterates (batch*heads, q_block, kv_block) with the kv dimension
  innermost — running max/sum/accumulator live in VMEM scratch across the
  kv sweep and the output block is written once on the final kv step;
* softmax statistics are kept as (block_q, 128) f32 tiles (lane-replicated)
  to match the VPU tile shape *inside* the kernel, but logsumexp is stored
  to HBM as a compact (bh, seq, 8) array (sublane-tile replication only);
* causal blocks strictly above the diagonal are skipped via predication;
  the diagonal block applies a triangular mask from 2D broadcasted_iota;
* logsumexp is saved for the backward pass, which recomputes P blockwise
  (dq kernel sweeps kv; dk/dv kernel sweeps q innermost).

SPMD note: a ``pallas_call`` is a manual computation that GSPMD cannot
auto-partition, so the raw kernel runs **one device per shard**. To compose
with GSPMD policies (DP/FSDP/TP), :func:`sharded_flash_attention` wraps the
kernel in ``shard_map`` — attention is embarrassingly parallel over
batch x heads, so batch shards over the (data, fsdp) axes and heads over
the model axis, matching the Megatron-style TP rules the model families
ship. ``attend(kernel='flash', mesh=...)`` routes there automatically.

``interpret=True`` runs the same kernels in interpreter mode for CPU tests.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusystem.ops.attention import NEG_INF

from tpusystem.ops.pallas import CompilerParams

LANES = 128  # VPU lane count: in-VMEM softmax stats are (block_q, LANES) tiles
G1_VMEM_LIMIT = 96 * 1024 * 1024  # scoped-VMEM budget requested by the
             # resident-dq fused backward; past its estimated working set
             # the backward auto-routes to the split sweeps.
STATS = 8    # trailing dim of HBM-stored lse/delta — the f32 sublane tile.
             # Mosaic requires the last two block dims divisible by (8, 128) or
             # equal to the array dims, so a compact (bh, seq) layout is not
             # lowerable; (bh, seq, 8) stores 8 replicated f32 per position,
             # 16x less HBM than lane-replicated (bh, seq, 128).


def _masked_scores(query, key, *, scale, causal, q_idx, kv_idx,
                   block_q, block_kv):
    """f32 (block_q, block_kv) scores with the causal mask applied.

    Shared by the forward, dq and dkv kernels so the mask/scale arithmetic
    cannot drift between forward and backward.
    """
    scores = jax.lax.dot_general(
        query, key, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) + q_idx * block_q
        cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) + kv_idx * block_kv
        scores = jnp.where(rows >= cols, scores, NEG_INF)
    return scores


def _visible(causal: bool, q_idx, kv_idx, block_q: int, block_kv: int):
    """Predicate: does this (q, kv) block intersect the causal triangle?"""
    return (not causal) or (q_idx * block_q + block_q - 1 >= kv_idx * block_kv)


def _keep_mask(seed, head_row, q_idx, kv_idx, block_q: int, block_kv: int,
               dropout: float):
    """Per-tile Bernoulli(1 - dropout) keep mask, reproducible by position.

    A counter-style hash (xorshift-multiply mixing) of the *global*
    (head-row, query-position, key-position) triple plus the step seed —
    not the sequential hardware PRNG — so the forward, dq, and dkv kernels
    regenerate byte-identical masks even though their grids sweep the
    tiles in different orders, and interpret mode (CPU tests) produces the
    same masks as the TPU lowering.
    """
    shape = (block_q, block_kv)
    rows = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
            + (q_idx * block_q).astype(jnp.uint32))
    cols = (jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
            + (kv_idx * block_kv).astype(jnp.uint32))
    x = rows * jnp.uint32(0x9E3779B1) ^ cols * jnp.uint32(0x85EBCA77)
    x = x + seed.astype(jnp.uint32) + head_row.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return (x >> 8) < jnp.uint32(int(round((1.0 - dropout) * (1 << 24))))


def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr,
                      *, scale: float, causal: bool,
                      block_q: int, block_kv: int, dropout: float):
    # program_id must be read at the kernel top level (not inside pl.when
    # bodies — interpret mode does not substitute it there)
    head, q_idx, kv_idx = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    kv_steps = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: skip blocks strictly above the diagonal
    @pl.when(_visible(causal, q_idx, kv_idx, block_q, block_kv))
    def _block():
        query = q_ref[0]                      # (block_q, head_dim)
        value = v_ref[0]
        scores = _masked_scores(query, k_ref[0], scale=scale, causal=causal,
                                q_idx=q_idx, kv_idx=kv_idx,
                                block_q=block_q, block_kv=block_kv)

        m_prev = m_scr[:, :1]                               # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        probs = jnp.exp(scores - m_new)                     # (block_q, block_kv)
        correction = jnp.exp(m_prev - m_new)                # (block_q, 1)
        # the softmax denominator accumulates UNmasked probabilities —
        # attention-probability dropout drops normalized weights, it does
        # not renormalize over survivors (the 'xla' path's semantics)
        l_new = correction * l_scr[:, :1] + jnp.sum(probs, axis=1, keepdims=True)
        if dropout:
            keep = _keep_mask(seed_ref[0], head, q_idx, kv_idx,
                              block_q, block_kv, dropout)
            contrib = probs * keep
        else:
            contrib = probs
        acc_scr[...] = acc_scr[...] * correction + jax.lax.dot_general(
            contrib.astype(value.dtype), value, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kv_idx == kv_steps - 1)
    def _finish():
        l_final = l_scr[:, :1]
        safe_l = jnp.where(l_final == 0.0, 1.0, l_final)
        out = acc_scr[...] / safe_l
        if dropout:
            out = out / (1.0 - dropout)       # inverted-dropout scaling
        o_ref[0] = out.astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(safe_l)                # (block_q, 1)
        lse_ref[0] = jnp.broadcast_to(lse, (lse.shape[0], STATS))


def _flash_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_ref,
                     dq_scr, *, scale: float, causal: bool,
                     block_q: int, block_kv: int, dropout: float):
    head, q_idx, kv_idx = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    kv_steps = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_visible(causal, q_idx, kv_idx, block_q, block_kv))
    def _block():
        key, value = k_ref[0], v_ref[0]
        grad_out = do_ref[0]
        scores = _masked_scores(q_ref[0], key, scale=scale, causal=causal,
                                q_idx=q_idx, kv_idx=kv_idx,
                                block_q=block_q, block_kv=block_kv)
        probs = jnp.exp(scores - lse_ref[0, :, :1])          # (block_q, 1)
        dprobs = jax.lax.dot_general(
            grad_out, value, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout:
            # d(out)/d(score): the kept-weight term carries the mask and
            # the 1/(1-p) scale; the softmax-denominator term keeps the
            # full (unmasked) probability — see the forward's l rule
            keep = _keep_mask(seed_ref[0], head, q_idx, kv_idx,
                              block_q, block_kv, dropout)
            dprobs = keep * dprobs / (1.0 - dropout)
        dscores = probs * (dprobs - delta_ref[0, :, :1]) * scale
        dq_scr[...] += jax.lax.dot_general(
            dscores.astype(key.dtype), key, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == kv_steps - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                      *, scale: float, causal: bool,
                      block_q: int, block_kv: int, q_steps: int, group: int,
                      dropout: float):
    # the innermost grid dim sweeps (group member, q block) pairs under
    # GQA: the q-block index for causal masking is its q_steps remainder,
    # and dk/dv accumulate across the whole sweep
    kv_idx, sweep = pl.program_id(1), pl.program_id(2)
    q_idx = sweep % q_steps
    # the mask row is the QUERY head's bh row (the forward hashed with
    # program_id(0) over B*Hq; this grid's dim 0 walks KV rows); read at
    # top level — interpret mode does not substitute program_id in when-bodies
    head_row = pl.program_id(0) * group + sweep // q_steps

    @pl.when(sweep == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_visible(causal, q_idx, kv_idx, block_q, block_kv))
    def _block():
        query, value = q_ref[0], v_ref[0]
        grad_out = do_ref[0]
        scores = _masked_scores(query, k_ref[0], scale=scale, causal=causal,
                                q_idx=q_idx, kv_idx=kv_idx,
                                block_q=block_q, block_kv=block_kv)
        probs = jnp.exp(scores - lse_ref[0, :, :1])           # (bq, bkv)
        dprobs = jax.lax.dot_general(
            grad_out, value, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout:
            keep = _keep_mask(seed_ref[0], head_row, q_idx, kv_idx,
                              block_q, block_kv, dropout)
            kept = probs * keep / (1.0 - dropout)
            dprobs = keep * dprobs / (1.0 - dropout)
        else:
            kept = probs
        dv_scr[...] += jax.lax.dot_general(
            kept.astype(grad_out.dtype), grad_out, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bkv, d)
        dscores = probs * (dprobs - delta_ref[0, :, :1]) * scale
        dk_scr[...] += jax.lax.dot_general(
            dscores.astype(query.dtype), query, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(sweep == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_block_terms(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, head_row, q_idx, kv_idx,
                     *, scale, causal, block_q, block_kv, dropout):
    """The backward block math shared by both fused kernels: recompute
    scores/probs once and return ``(kept, dscores, query, key, grad_out)``
    — ``kept`` feeds dv (mask-and-rescaled under dropout), ``dscores``
    feeds dk and dq. One definition so the GQA partial-array kernel and
    the MHA resident-dq kernel cannot drift numerically."""
    query, key, value = q_ref[0], k_ref[0], v_ref[0]
    grad_out = do_ref[0]
    scores = _masked_scores(query, key, scale=scale, causal=causal,
                            q_idx=q_idx, kv_idx=kv_idx,
                            block_q=block_q, block_kv=block_kv)
    probs = jnp.exp(scores - lse_ref[0, :, :1])               # (bq, bkv)
    dprobs = jax.lax.dot_general(
        grad_out, value, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if dropout:
        keep = _keep_mask(seed_ref[0], head_row, q_idx, kv_idx,
                          block_q, block_kv, dropout)
        kept = probs * keep / (1.0 - dropout)
        dprobs = keep * dprobs / (1.0 - dropout)
    else:
        kept = probs
    dscores = probs * (dprobs - delta_ref[0, :, :1]) * scale
    return kept, dscores, query, key, grad_out


def _flash_fused_bwd_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                            delta_ref, dq_ref, dk_ref, dv_ref,
                            dk_scr, dv_scr,
                            *, scale: float, causal: bool,
                            block_q: int, block_kv: int, group: int,
                            dropout: float):
    """Single-pass backward: dq, dk and dv from ONE score recomputation.

    The split backward (:func:`_flash_dq_kernel` + :func:`_flash_dkv_kernel`)
    computes ``scores = q k^T`` and ``dprobs = do v^T`` twice per visible
    block — once per kernel. Fused, the seven backward matmuls drop to five
    (scores, dprobs, dv, dk, dq), a 2/7 cut of the backward's MXU work.

    Grid layout (the splash-attention fused-backward shape): ``(kv_steps,
    bh, q_steps)`` with the KV dimension OUTERMOST. Within one kv section
    every query head of a KV group and every q block revisit the same
    dk/dv output block consecutively, so dk/dv accumulate in VMEM scratch
    and flush once per (kv head, kv block). dq cannot accumulate across
    the outer kv dimension (non-consecutive revisits), so each grid step
    writes its partial to a ``(kv_steps, bh, seq_q, d)`` output that the
    caller reduces with a plain sum — free at the headline tiling where
    kv_steps == 1.
    """
    kv_idx, head_row, q_idx = (pl.program_id(0), pl.program_id(1),
                               pl.program_id(2))
    q_steps = pl.num_programs(2)

    @pl.when(jnp.logical_and(head_row % group == 0, q_idx == 0))
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    visible = _visible(causal, q_idx, kv_idx, block_q, block_kv)

    @pl.when(visible)
    def _block():
        kept, dscores, query, key, grad_out = _bwd_block_terms(
            seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            head_row, q_idx, kv_idx, scale=scale, causal=causal,
            block_q=block_q, block_kv=block_kv, dropout=dropout)
        dv_scr[...] += jax.lax.dot_general(
            kept.astype(grad_out.dtype), grad_out, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bkv, d)
        dk_scr[...] += jax.lax.dot_general(
            dscores.astype(query.dtype), query, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_ref[0, 0] = jax.lax.dot_general(
            dscores.astype(key.dtype), key, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)

    @pl.when(jnp.logical_not(visible))
    def _skip():
        # the partial-dq block is written every step (revisit semantics
        # would otherwise leave the previous block's bytes in the buffer)
        dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])

    @pl.when(jnp.logical_and(head_row % group == group - 1,
                             q_idx == q_steps - 1))
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_fused_bwd_g1_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref,
                               lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                               dk_scr, dv_scr,
                               *, scale: float, causal: bool,
                               block_q: int, block_kv: int, dropout: float):
    """Fused backward without the partial-dq array (``group == 1``).

    Grid ``(bh, kv_steps, q_steps)``: for one head row, every (kv, q)
    block maps to the SAME f32 dq output block ``(1, seq_q, d)``, which
    Pallas keeps resident in VMEM across the whole row — dq accumulates
    in place in float32 and is written to HBM once per row (single
    rounding, zero partial traffic; the ``(kv_steps, ...)`` partial array
    of :func:`_flash_fused_bwd_kernel` costs ~2% MFU at seq 16k). dk/dv
    accumulate in scratch across each kv row's q sweep as usual. GQA
    (group > 1) cannot use this layout — a KV head's dk/dv revisits are
    non-consecutive when bh is outermost — and keeps the partial-array
    kernel."""
    kv_idx, q_idx = pl.program_id(1), pl.program_id(2)
    head = pl.program_id(0)
    kv_steps, q_steps = pl.num_programs(1), pl.num_programs(2)

    @pl.when(jnp.logical_and(kv_idx == 0, q_idx == 0))
    def _init_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    @pl.when(q_idx == 0)
    def _init_dkv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_visible(causal, q_idx, kv_idx, block_q, block_kv))
    def _block():
        kept, dscores, query, key, grad_out = _bwd_block_terms(
            seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            head, q_idx, kv_idx, scale=scale, causal=causal,
            block_q=block_q, block_kv=block_kv, dropout=dropout)
        dv_scr[...] += jax.lax.dot_general(
            kept.astype(grad_out.dtype), grad_out, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bkv, d)
        dk_scr[...] += jax.lax.dot_general(
            dscores.astype(query.dtype), query, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        rows = pl.ds(q_idx * block_q, block_q)
        dq_ref[0, rows, :] += jax.lax.dot_general(
            dscores.astype(key.dtype), key, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_idx == q_steps - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _fit_block(seq: int, want: int, granule: int = LANES) -> int | None:
    """Largest lane-aligned divisor of ``seq`` that is <= ``want``.

    Keeps mid-size sequence lengths (768, 1536, ...) on the flash kernel
    with a smaller tile instead of silently dropping to the O(seq^2) XLA
    fallback when the requested tile does not divide them. Sequences at or
    under one granule run as a single block; sequences that no aligned
    tile divides return None (XLA fallback).
    """
    want = min(want, seq)
    if seq <= granule:
        # single block, if it tiles onto the sublanes; otherwise XLA
        return seq if seq % 8 == 0 else None
    best = None
    for candidate in range(granule, want + 1, granule):
        if seq % candidate == 0:
            best = candidate
    return best


def _block_sizes(seq_q: int, seq_kv: int, block_q: int, block_kv: int):
    block_q = _fit_block(seq_q, block_q)
    block_kv = _fit_block(seq_kv, block_kv)
    if block_q is None or block_kv is None:
        return None
    return block_q, block_kv


def _flash_fwd(q, k, v, seed, causal, scale, block_q, block_kv, interpret,
               group=1, dropout=0.0):
    """q: [B*Hq, S, D]; k/v: [B*Hkv, S, D] with Hq = Hkv * group.

    GQA lives entirely in the index maps: query row ``i`` reads KV row
    ``i // group`` (b-major head layout makes that exact), so grouped KV
    is never materialized at the query head count. ``seed`` is a [1] int32
    (SMEM) feeding the positional dropout hash. Returns
    (out, residuals)."""
    bh, seq_q, head_dim = q.shape
    seq_kv = k.shape[1]
    grid = (bh, seq_q // block_q, seq_kv // block_kv)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_kv=block_kv, dropout=dropout)
    # the seed input exists only on the dropout path, so the dropout=0
    # program (the perf-critical one) is identical to a seedless build
    seed_args, seed_specs, kernel = _seed_wiring(kernel, seed, dropout)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, head_dim), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((1, block_kv, head_dim),
                         lambda i, j, k_: (i // group, k_, 0)),
            pl.BlockSpec((1, block_kv, head_dim),
                         lambda i, j, k_: (i // group, k_, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((1, block_q, STATS), lambda i, j, k_: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, STATS), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(*seed_args, q, k, v)
    return out, (q, k, v, seed, out, lse)


def _seed_wiring(kernel, seed, dropout):
    """Seed input wiring: present only when dropout is active (the
    dropout=0 kernels never read it, and omitting the argument keeps the
    hot-path program identical to a seedless build). Returns
    ``(extra_args, extra_in_specs, kernel)``."""
    if dropout:
        return (seed,), [pl.BlockSpec(memory_space=pltpu.SMEM)], kernel
    return (), [], functools.partial(kernel, None)


def _flash_bwd_impl(causal, scale, block_q, block_kv, interpret, group,
                    dropout, backward, residuals, grad_out, grad_lse):
    """Backward for :func:`_flash_lse`. ``grad_lse`` (bh, seq_q) is the
    cotangent of the logsumexp output (ring attention merges chunk results
    by lse, so gradient flows into it; plain ``flash_attention`` discards
    lse and its cotangent arrives as zeros); per-score gradient is
    p*(dprobs - (delta - dlse)), so it folds into the precomputed delta
    term. Under dropout the kernels regenerate the forward's positional
    keep masks from the same seed.

    ``backward``: ``'fused'`` runs the single-pass dq+dk+dv kernel (one
    score recomputation per block — 5 backward matmuls instead of 7);
    ``'split'`` keeps the separate dq / dkv sweeps — the manual A/B
    reference. The resident-dq fused variant (group 1, multi-kv-step)
    additionally auto-routes to ``'split'`` when its estimated VMEM
    working set (whole-row f32 dq + block IO + f32 score intermediates)
    exceeds the 96 MB limit it requests — the one fused layout whose
    working set grows with ``seq_q`` rather than the block sizes."""
    q, k, v, seed, out, lse = residuals
    bh, seq_q, head_dim = q.shape
    seq_kv = k.shape[1]
    delta = jnp.sum(grad_out.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # (bh, seq_q, 1)
    if grad_lse is not None:
        delta = delta - grad_lse.astype(jnp.float32)[..., None]
    delta = jnp.broadcast_to(delta, (bh, seq_q, STATS))

    resident_dq = backward == 'fused' and group == 1 and seq_kv > block_kv
    if resident_dq:
        # Conservative working-set estimate for the resident-dq layout:
        # whole-row f32 dq, double-buffered input blocks, f32 dk/dv
        # scratch, and ~3 f32 (block_q, block_kv) score intermediates.
        # Past the limit requested below, Mosaic would fail the
        # pallas_call — route to the split sweeps instead (block-sized
        # working set, independent of seq_q).
        g1_bytes = (4 * seq_q * head_dim
                    + 2 * q.dtype.itemsize * (3 * block_q + 2 * block_kv)
                    * head_dim
                    + 2 * 4 * block_kv * head_dim
                    + 3 * 4 * block_q * block_kv)
        if g1_bytes > G1_VMEM_LIMIT:
            warnings.warn(
                f"fused flash backward: estimated VMEM working set "
                f"{g1_bytes / 2**20:.1f} MB exceeds the "
                f"{G1_VMEM_LIMIT >> 20} MB limit at this (seq, block) "
                "combination; falling back to the split dq/dkv sweeps.",
                stacklevel=2)
            backward, resident_dq = 'split', False
    if resident_dq:
        # multi-kv-step MHA: accumulate dq in a resident f32 output block
        # (no partial array, single rounding — see the kernel docstring).
        # The whole-row dq block plus the f32 score intermediates exceed
        # the default scoped-VMEM budget at long seq; raise the limit.
        kv_steps, q_steps = seq_kv // block_kv, seq_q // block_q
        kernel = functools.partial(
            _flash_fused_bwd_g1_kernel, scale=scale, causal=causal,
            block_q=block_q, block_kv=block_kv, dropout=dropout)
        seed_args, seed_specs, kernel = _seed_wiring(kernel, seed, dropout)
        q_row = lambda i, kv, j: (i, j, 0)
        kv_row = lambda i, kv, j: (i, kv, 0)
        dq_f32, dk, dv = pl.pallas_call(
            kernel,
            grid=(bh, kv_steps, q_steps),
            in_specs=seed_specs + [
                pl.BlockSpec((1, block_q, head_dim), q_row),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
                pl.BlockSpec((1, block_q, head_dim), q_row),
                pl.BlockSpec((1, block_q, STATS), q_row),
                pl.BlockSpec((1, block_q, STATS), q_row),
            ],
            out_specs=[
                pl.BlockSpec((1, seq_q, head_dim), lambda i, kv, j: (i, 0, 0)),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, seq_q, head_dim), jnp.float32),
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_kv, head_dim), jnp.float32),
                pltpu.VMEM((block_kv, head_dim), jnp.float32),
            ],
            compiler_params=CompilerParams(
                vmem_limit_bytes=G1_VMEM_LIMIT),
            interpret=interpret,
        )(*seed_args, q, k, v, grad_out, lse, delta)
        dq = dq_f32.astype(q.dtype)
        return dq, dk, dv, np.zeros(seed.shape, jax.dtypes.float0)

    if backward == 'fused':
        kv_steps, q_steps = seq_kv // block_kv, seq_q // block_q
        kernel = functools.partial(
            _flash_fused_bwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_kv=block_kv, group=group, dropout=dropout)
        seed_args, seed_specs, kernel = _seed_wiring(kernel, seed, dropout)
        q_row = lambda kv, i, j: (i, j, 0)
        kv_row = lambda kv, i, j: (i // group, kv, 0)
        # partials in f32 when they will be summed across kv steps: bf16
        # rounding before a 16-way sum (seq 16k at 1024 tiles) would make
        # dq noisier than the split path's f32 scratch accumulation; at
        # kv_steps == 1 (headline) the sum is a copy and q.dtype is exact
        partial_dtype = q.dtype if kv_steps == 1 else jnp.float32
        dq_partial, dk, dv = pl.pallas_call(
            kernel,
            grid=(kv_steps, bh, q_steps),
            in_specs=seed_specs + [
                pl.BlockSpec((1, block_q, head_dim), q_row),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
                pl.BlockSpec((1, block_q, head_dim), q_row),
                pl.BlockSpec((1, block_q, STATS), q_row),
                pl.BlockSpec((1, block_q, STATS), q_row),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, head_dim),
                             lambda kv, i, j: (kv, i, j, 0)),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
                pl.BlockSpec((1, block_kv, head_dim), kv_row),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((kv_steps, bh, seq_q, head_dim),
                                     partial_dtype),
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_kv, head_dim), jnp.float32),
                pltpu.VMEM((block_kv, head_dim), jnp.float32),
            ],
            interpret=interpret,
        )(*seed_args, q, k, v, grad_out, lse, delta)
        dq = jnp.sum(dq_partial, axis=0, dtype=jnp.float32).astype(q.dtype)
        return dq, dk, dv, np.zeros(seed.shape, jax.dtypes.float0)

    dq_kernel = functools.partial(
        _flash_dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_kv=block_kv, dropout=dropout)
    seed_args, seed_specs, dq_kernel = _seed_wiring(dq_kernel, seed, dropout)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, seq_q // block_q, seq_kv // block_kv),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, head_dim), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((1, block_kv, head_dim),
                         lambda i, j, k_: (i // group, k_, 0)),
            pl.BlockSpec((1, block_kv, head_dim),
                         lambda i, j, k_: (i // group, k_, 0)),
            pl.BlockSpec((1, block_q, head_dim), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((1, block_q, STATS), lambda i, j, k_: (i, j, 0)),
            pl.BlockSpec((1, block_q, STATS), lambda i, j, k_: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda i, j, k_: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(*seed_args, q, k, v, grad_out, lse, delta)

    q_steps = seq_q // block_q
    dkv_kernel = functools.partial(
        _flash_dkv_kernel, scale=scale, causal=causal,
        block_q=block_q, block_kv=block_kv, q_steps=q_steps, group=group,
        dropout=dropout)
    seed_args, seed_specs, dkv_kernel = _seed_wiring(dkv_kernel, seed, dropout)
    # grid dim 0 walks KV rows; the innermost dim sweeps every (group
    # member, q block) pair so one kv head's dk/dv accumulates over all
    # the query heads that shared it
    row = lambda i, k_, j: (i * group + j // q_steps, j % q_steps, 0)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh // group, seq_kv // block_kv, q_steps * group),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, head_dim), row),
            pl.BlockSpec((1, block_kv, head_dim), lambda i, k_, j: (i, k_, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda i, k_, j: (i, k_, 0)),
            pl.BlockSpec((1, block_q, head_dim), row),
            pl.BlockSpec((1, block_q, STATS), row),
            pl.BlockSpec((1, block_q, STATS), row),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, head_dim), lambda i, k_, j: (i, k_, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda i, k_, j: (i, k_, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(*seed_args, q, k, v, grad_out, lse, delta)
    return dq, dk, dv, np.zeros(seed.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash_lse(q, k, v, seed, causal, scale, block_q, block_kv, interpret,
               group, dropout, backward):
    (out, lse), _ = _flash_lse_fwd(q, k, v, seed, causal, scale, block_q,
                                   block_kv, interpret, group, dropout,
                                   backward)
    return out, lse


def _flash_lse_fwd(q, k, v, seed, causal, scale, block_q, block_kv, interpret,
                   group, dropout, backward):
    out, residuals = _flash_fwd(q, k, v, seed, causal, scale, block_q,
                                block_kv, interpret, group, dropout)
    lse = residuals[5][..., 0]                                # (bh, seq_q)
    return (out, lse), residuals


def _flash_lse_bwd(causal, scale, block_q, block_kv, interpret, group,
                   dropout, backward, residuals, grads):
    grad_out, grad_lse = grads
    return _flash_bwd_impl(causal, scale, block_q, block_kv, interpret,
                           group, dropout, backward, residuals, grad_out,
                           grad_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(query, key, value, *, causal: bool = True,
                    scale: float | None = None,
                    block_q: int = 1024, block_kv: int = 1024,
                    interpret: bool | None = None,
                    dropout: float = 0.0, dropout_rng=None,
                    backward: str = 'fused'):
    """Flash attention over [batch, length, heads, head_dim] tensors.

    Drop-in for :func:`tpusystem.ops.attention.dot_product_attention`
    (GQA handled in-kernel: grouped KV is shared across each query-head
    group via the block index maps, never broadcast) in single-device-per-shard
    contexts — see the module docstring for the GSPMD caveat. Falls back to
    the XLA path when the sequence length does not divide the block sizes.
    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    model code runs in CPU tests.

    ``dropout > 0`` (with ``dropout_rng``) drops attention probabilities
    in-kernel with the 'xla' path's semantics: normalized weights are
    dropped (no renormalization over survivors) and survivors scale by
    ``1/(1-p)``. Masks come from a positional counter hash seeded by
    ``dropout_rng``, regenerated identically in the backward kernels —
    nothing O(seq^2) is ever stored.

    Thin front of :func:`flash_attention_lse`: the discarded lse output
    costs nothing (the kernel computes it regardless) and its zero
    cotangent folds to a no-op in the shared backward.
    """
    out, _ = flash_attention_lse(query, key, value, causal=causal,
                                 scale=scale, block_q=block_q,
                                 block_kv=block_kv, interpret=interpret,
                                 dropout=dropout, dropout_rng=dropout_rng,
                                 backward=backward)
    return out


def flash_attention_lse(query, key, value, *, causal: bool = True,
                        scale: float | None = None,
                        block_q: int = 1024, block_kv: int = 1024,
                        interpret: bool | None = None,
                        dropout: float = 0.0, dropout_rng=None,
                        backward: str = 'fused'):
    """Flash attention that also returns the softmax logsumexp.

    Returns ``(out [B,S,H,D], lse [B,S,H] float32)``. The lse output is what
    lets blockwise results merge exactly: ring attention computes each KV
    chunk's ``(out_i, lse_i)`` independently and combines them with
    logsumexp weights (see :mod:`tpusystem.ops.ring`). Differentiable in
    both outputs — the lse cotangent folds into the backward kernels' delta
    term. Falls back to a differentiable XLA path (explicit scores +
    logsumexp) when no lane-aligned block divides the sequence.

    ``dropout``/``dropout_rng``: in-kernel attention-probability dropout
    (see :func:`flash_attention`). The lse output stays the FULL softmax
    denominator (dropout does not renormalize), so blockwise merges are
    unaffected.

    ``backward='fused'`` (default) runs the single-pass dq+dk+dv backward
    kernel — one score recomputation per block, 5 matmuls instead of the
    split path's 7; ``'split'`` keeps the separate dq / dkv kernels (the
    A/B reference and large-tile fallback; see :func:`_flash_bwd_impl`).
    """
    if interpret is None:
        interpret = jax.default_backend() not in ('tpu', 'axon')
    if dropout:
        if dropout_rng is None:
            raise ValueError('dropout > 0 needs a dropout_rng key')
        seed = jax.random.randint(dropout_rng, (1,), 0, jnp.iinfo(jnp.int32).max,
                                  dtype=jnp.int32)
    else:
        seed = jnp.zeros((1,), jnp.int32)

    batch, seq_q, q_heads, head_dim = query.shape
    kv_heads = key.shape[2]
    assert q_heads % kv_heads == 0, (
        f'query heads ({q_heads}) must be a multiple of KV heads '
        f'({kv_heads}) for grouped-query attention')
    # GQA stays grouped: the kernel maps each query head to its KV head via
    # the block index maps, so KV is never materialized q_heads wide
    group = q_heads // kv_heads
    scale = scale if scale is not None else head_dim ** -0.5

    if backward not in ('fused', 'split'):
        raise ValueError(f"backward must be 'fused' or 'split', got {backward!r}")
    # Tile-size note (measured on v5e, seq 8k-16k MHA): kv-2048 tiles are
    # 6-9% faster on the isolated fwd+bwd attention chain, but the WHOLE
    # training step with remat is 2-5% slower (the rematerialized forward
    # runs twice and loses more at 2048 than the backward gains), so the
    # 1024/1024 default stands; pass block_kv explicitly to override.
    sizes = _block_sizes(seq_q, key.shape[1], block_q, block_kv)
    if sizes is None:
        from tpusystem.ops.attention import repeat_kv_heads
        key, value = repeat_kv_heads(query, key, value)
        return _xla_attention_lse(query, key, value, causal=causal,
                                  scale=scale, dropout=dropout,
                                  dropout_rng=dropout_rng)
    block_q, block_kv = sizes

    def to_bh(tensor):  # [B,S,H,D] -> [B*H, S, D]
        return tensor.transpose(0, 2, 1, 3).reshape(-1, tensor.shape[1], head_dim)

    out, lse = _flash_lse(to_bh(query), to_bh(key), to_bh(value), seed,
                          causal, scale, block_q, block_kv, interpret, group,
                          float(dropout), backward)
    out = out.reshape(batch, q_heads, seq_q, head_dim).transpose(0, 2, 1, 3)
    lse = lse.reshape(batch, q_heads, seq_q).transpose(0, 2, 1)
    return out, lse


def _xla_attention_lse(query, key, value, *, causal: bool, scale: float,
                       dropout: float = 0.0, dropout_rng=None):
    """Reference (out, lse) pair in plain XLA ops — the fallback for
    sequence lengths the kernel cannot tile, and the 'einsum' inner kernel
    of ring attention."""
    from tpusystem.ops.attention import causal_mask

    scores = jnp.einsum('bqhd,bkhd->bhqk', query, key,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        scores = jnp.where(causal_mask(query.shape[1], key.shape[1]),
                           scores, NEG_INF)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)        # [B,H,Q]
    weights = jnp.exp(scores - lse[..., None])
    if dropout and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout, weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout), 0.0)
    out = jnp.einsum('bhqk,bkhd->bqhd', weights.astype(value.dtype), value)
    return out, lse.transpose(0, 2, 1)                        # lse -> [B,S,H]


def sharded_flash_attention(query, key, value, mesh, *, causal: bool = True,
                            scale: float | None = None,
                            dropout: float = 0.0, dropout_rng=None):
    """Flash attention composed with GSPMD policies via ``shard_map``.

    Attention is embarrassingly parallel over batch x heads: batch shards
    over the (data, fsdp) mesh axes and heads over the model axis — the
    layout the TP partition rules already give the QKV projections — and
    the Pallas kernel runs independently per shard. Differentiable (the
    kernel's ``custom_vjp`` composes with ``shard_map``'s transpose).

    Axes that do not divide the corresponding tensor dimension are left
    replicated (e.g. ``module.init`` traces with batch 1). Under GQA the
    KV-head axis shards over ``model`` when divisible; otherwise KV heads
    are broadcast up to the query head count first.
    """
    from math import prod

    from jax.sharding import PartitionSpec as P

    from tpusystem.ops.attention import repeat_kv_heads
    from tpusystem.parallel.mesh import DATA, FSDP, MODEL, shard_map

    shape = dict(mesh.shape)
    batch_axes = tuple(axis for axis in (DATA, FSDP) if shape.get(axis, 1) > 1)
    if batch_axes and query.shape[0] % prod(shape[a] for a in batch_axes):
        batch_axes = ()
    model = shape.get(MODEL, 1)
    head_axis = MODEL if model > 1 and query.shape[2] % model == 0 else None
    if head_axis and key.shape[2] % model:
        warnings.warn(
            f"sharded_flash_attention: {key.shape[2]} KV heads do not divide "
            f"the model axis ({model}); broadcasting KV to the "
            f"{query.shape[2]} query heads. This is correct but forfeits the "
            "GQA KV memory saving on this mesh — pick a model axis that "
            "divides the KV head count to keep grouped KV.",
            stacklevel=2)
        key, value = repeat_kv_heads(query, key, value)

    spec = P(batch_axes or None, None, head_axis, None)

    # check_vma=False: pallas_call out_shapes carry no varying-mesh-axis
    # info, so shard_map's replication checker cannot see through the kernel
    @functools.partial(shard_map, mesh=mesh, check_vma=False,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def mapped(q, k, v):
        rng = dropout_rng
        if dropout and rng is not None:
            # decorrelate the dropout masks across shards (the positional
            # hash would otherwise repeat per local batch/head index)
            for axis in (DATA, FSDP, MODEL):
                if shape.get(axis, 1) > 1:
                    rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               dropout=dropout, dropout_rng=rng)

    return mapped(query, key, value)
