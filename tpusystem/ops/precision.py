"""Mixed-precision matmul helpers shared by every LM head.

One rule, applied everywhere a head projects features onto a vocabulary:
operands in the compute dtype (bf16 — MXU rate), accumulation and result in
float32 (loss-stable softmax). Centralized so the GPT-2 tied head, the
pipelined variant, the Llama untied head, and the fused chunked loss stay
numerically in lockstep.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def f32_accum_dot(a, b, dimension_numbers, precision=None,
                  preferred_element_type=None):
    """``lax.dot_general`` that always accumulates into float32 (the
    ``preferred_element_type`` argument of callers is deliberately ignored —
    this signature doubles as a ``flax.linen.Dense`` ``dot_general=``)."""
    return jax.lax.dot_general(a, b, dimension_numbers, precision=precision,
                               preferred_element_type=jnp.float32)


def head_logits(features, table, *, tied: bool | None = None) -> jax.Array:
    """Project ``[..., dim]`` features onto the vocabulary: f32 logits from
    compute-dtype operands.

    ``tied=True`` means ``table`` is a ``[vocab, dim]`` embedding table
    (GPT-2 convention); ``tied=False`` a ``[dim, vocab]`` head kernel
    (Llama convention). ``tied=None`` infers from the shapes but refuses a
    square table, where the orientation is ambiguous and guessing would
    silently transpose the head."""
    dim = features.shape[-1]
    if tied is None:
        if table.shape[0] == table.shape[1]:
            raise ValueError(
                f'square head table {table.shape}: pass tied= explicitly')
        tied = table.shape[-1] == dim
    table_dim = 1 if tied else 0
    if table.shape[table_dim] != dim:
        raise ValueError(
            f'feature dim {dim} does not match table {table.shape} '
            f'(tied={tied})')
    features = features.astype(table.dtype)
    return f32_accum_dot(
        features, table, (((features.ndim - 1,), (table_dim,)), ((), ())))
