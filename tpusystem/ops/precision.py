"""Mixed-precision matmul helpers shared by every LM head — and the
quantized weight-streaming pair behind the serving-path decode.

Two rule sets live here:

* **Head precision** (training): operands in the compute dtype (bf16 —
  MXU rate), accumulation and result in float32 (loss-stable softmax).
  Centralized so the GPT-2 tied head, the pipelined variant, the Llama
  untied head, and the fused chunked loss stay numerically in lockstep.

* **Streamed quantization** (decode): small-batch decode is weight-
  STREAMING bound (benchmarks/decode_roofline.py), so the lever is HBM
  bytes per step. :func:`quantize_streamed` rounds the decoder's matrix
  params to int8/fp8 with **per-output-channel symmetric scales**
  computed once at stream time; :func:`qdot` is the matching matmul —
  the scale is a per-column constant, so it factors out of the
  contraction exactly (``x @ (q * s) == (x @ q) * s``) and is applied
  once to the f32 accumulator, never to the streamed tiles. Vector
  leaves (biases, layernorms), embedding tables, and MoE routers stay
  untouched — the same exclusion rule the decode caster applies
  (``tpusystem.train.generate``), for the same reasons.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


def f32_accum_dot(a, b, dimension_numbers, precision=None,
                  preferred_element_type=None):
    """``lax.dot_general`` that always accumulates into float32 (the
    ``preferred_element_type`` argument of callers is deliberately ignored —
    this signature doubles as a ``flax.linen.Dense`` ``dot_general=``)."""
    return jax.lax.dot_general(a, b, dimension_numbers, precision=precision,
                               preferred_element_type=jnp.float32)


def head_logits(features, table, *, tied: bool | None = None) -> jax.Array:
    """Project ``[..., dim]`` features onto the vocabulary: f32 logits from
    compute-dtype operands.

    ``tied=True`` means ``table`` is a ``[vocab, dim]`` embedding table
    (GPT-2 convention); ``tied=False`` a ``[dim, vocab]`` head kernel
    (Llama convention). ``tied=None`` infers from the shapes but refuses a
    square table, where the orientation is ambiguous and guessing would
    silently transpose the head."""
    dim = features.shape[-1]
    if tied is None:
        if table.shape[0] == table.shape[1]:
            raise ValueError(
                f'square head table {table.shape}: pass tied= explicitly')
        tied = table.shape[-1] == dim
    table_dim = 1 if tied else 0
    if table.shape[table_dim] != dim:
        raise ValueError(
            f'feature dim {dim} does not match table {table.shape} '
            f'(tied={tied})')
    features = features.astype(table.dtype)
    return f32_accum_dot(
        features, table, (((features.ndim - 1,), (table_dim,)), ((), ())))


# --- streamed quantization (serving-path decode) -------------------------

# symmetric range per streamable narrow dtype: int8 uses the full signed
# range minus the asymmetric -128 (so negation is exact), fp8 e4m3fn its
# largest finite (the cast saturates NaN-ward past it, hence the clip in
# the quantizer)
QMAX = {'int8': 127.0, 'fp8': 448.0}


def _qdtype(mode: str):
    if mode == 'int8':
        return jnp.dtype(jnp.int8)
    if mode == 'fp8':
        return jnp.dtype(jnp.float8_e4m3fn)
    raise ValueError(f'unknown quantized stream mode {mode!r}; '
                     f"expected one of {tuple(QMAX)}")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedLeaf:
    """A matrix param streamed narrow: ``values`` (int8/fp8, the original
    leaf's shape) plus per-output-channel f32 ``scales`` (the leaf's
    shape with the contraction dim — second-to-last — reduced to 1), so
    ``values * scales`` broadcasts back to the dequantized matrix. A
    registered pytree node: quantized param trees pass through ``jit``
    boundaries and ``tree_map`` like plain trees."""

    values: jax.Array
    scales: jax.Array

    def tree_flatten(self):
        return (self.values, self.scales), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    @property
    def shape(self):
        return self.values.shape

    @property
    def nbytes(self) -> int:
        return self.values.nbytes + self.scales.nbytes


def quantize_leaf(leaf, mode: str) -> QuantizedLeaf:
    """Per-output-channel symmetric quantization of one ``[..., in, out]``
    matrix: ``scales = absmax(leaf, axis=-2) / QMAX``, values rounded
    (int8) or cast (fp8) after clipping into the representable range.
    All-zero columns get scale 1 so the dequant stays finite."""
    qdtype = _qdtype(mode)
    qmax = QMAX[mode]
    wide = leaf.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wide), axis=-2, keepdims=True)
    scales = jnp.where(absmax > 0.0, absmax, qmax) / qmax
    scaled = jnp.clip(wide / scales, -qmax, qmax)
    if mode == 'int8':
        scaled = jnp.round(scaled)
    return QuantizedLeaf(scaled.astype(qdtype), scales)


def dequantize_leaf(leaf: QuantizedLeaf, compute=None) -> jax.Array:
    """``values * scales`` in f32, rounded once to ``compute`` (default:
    float32) — what the non-fused decode path feeds the model per step."""
    wide = leaf.values.astype(jnp.float32) * leaf.scales
    return wide if compute is None else wide.astype(compute)


def _is_quantized(node) -> bool:
    return isinstance(node, QuantizedLeaf)


def quantize_streamed(params, mode: str):
    """Quantize a param tree's streamed matrices to ``mode``
    (``'int8'``/``'fp8'``), leaving every other leaf untouched.

    The leaf rule is exactly the decode caster's
    (:func:`tpusystem.train.generate._caster`): float matrices with
    ``ndim >= 2``, excluding embedding tables (the embed step adds
    wte+wpe rows in f32; for a tied head the table must stay exact) and
    MoE routers (f32 gate logits — a quantized router could flip
    near-tie expert choices). Biases and layernorm params are vectors
    and fall through unchanged. Jit this once per mode
    (``generate``'s ``_quantizer`` cache) — an uncached quantize would
    retrace per call, the round-5 trap."""
    qdtype = _qdtype(mode)   # validates the mode eagerly
    del qdtype

    def quantize(path, leaf):
        from tpusystem.parallel.sharding import leaf_path
        path = leaf_path(path)
        if 'embedding' in path or 'router' in path:
            return leaf
        if leaf.ndim >= 2 and jnp.issubdtype(leaf.dtype, jnp.floating):
            return quantize_leaf(leaf, mode)
        return leaf

    return jax.tree_util.tree_map_with_path(quantize, params)


def dequantize_streamed(params, compute=None):
    """Replace every :class:`QuantizedLeaf` in ``params`` with its
    dequantized matrix (identity — the same tree object — when nothing
    is quantized, so wrapping an unquantized path costs nothing and
    changes no bits)."""
    leaves = jax.tree_util.tree_leaves(params, is_leaf=_is_quantized)
    if not any(_is_quantized(leaf) for leaf in leaves):
        return params
    return jax.tree_util.tree_map(
        lambda leaf: dequantize_leaf(leaf, compute) if _is_quantized(leaf)
        else leaf,
        params, is_leaf=_is_quantized)


def qdot(x, w, *, compute=None):
    """Quantization-aware ``x @ w`` with f32 accumulation.

    For a :class:`QuantizedLeaf`, the streamed narrow values are the
    matmul operand (cast to the compute dtype tile-side — the form whose
    HBM traffic is the narrow bytes) and the per-channel scale multiplies
    the f32 accumulator once — the exact epilogue the Pallas decode
    kernels apply, so this is their einsum fallback/reference. Plain
    arrays degrade to a cast matmul. Returns float32."""
    contract = (((x.ndim - 1,), (0,)), ((), ()))
    if isinstance(w, QuantizedLeaf):
        compute = jnp.dtype(compute or x.dtype)
        product = f32_accum_dot(x, w.values.astype(compute), contract)
        return product * w.scales.reshape(-1)
    return f32_accum_dot(x, w.astype(compute or x.dtype), contract)


@functools.lru_cache(maxsize=None)
def fp8_unsupported_reason() -> str | None:
    """Capability probe: can this jax/jaxlib cast to and matmul from
    ``float8_e4m3fn`` on the current backend? ``None`` when it can, else
    a reason string for ``pytest.mark.skipif`` / the ``stream_dtype=
    'fp8'`` gate. Cached in-process AND on disk keyed by the
    jax/jaxlib/python versions and backend (the PartitionId probe's
    discipline, ``parallel/mesh.py``) so the probe compiles once per
    installation. Unlike that probe this one runs in-process: an
    unsupported fp8 dtype raises a catchable TypeError/not-implemented,
    it never hard-aborts the runtime."""
    import pathlib
    import sys
    import tempfile
    try:
        import jaxlib
        jaxlib_version = getattr(jaxlib, '__version__', '?')
    except ImportError:
        jaxlib_version = '?'
    key = (f'{jax.__version__}-{jaxlib_version}-'
           f'py{sys.version_info[0]}.{sys.version_info[1]}-'
           f'{jax.default_backend()}')
    cache = pathlib.Path(tempfile.gettempdir()) / f'tpusystem-fp8-{key}.txt'
    try:
        cached = cache.read_text()
        return None if cached == 'ok' else cached
    except OSError:
        pass
    if not hasattr(jnp, 'float8_e4m3fn'):
        reason = 'this jax has no float8_e4m3fn dtype'
    else:
        try:
            @jax.jit
            def probe(x):
                narrow = x.astype(jnp.float8_e4m3fn)
                return f32_accum_dot(narrow.astype(jnp.float32), narrow
                                     .astype(jnp.float32),
                                     (((1,), (0,)), ((), ())))
            total = float(jnp.sum(probe(jnp.ones((8, 8), jnp.float32))))
            reason = (None if total == 8.0 ** 3
                      else f'fp8 round trip returned {total}, expected 512')
        except Exception as error:   # unsupported lowering on this backend
            reason = f'fp8 ops failed on {jax.default_backend()}: ' \
                     f'{str(error)[:200]}'
    try:
        cache.write_text('ok' if reason is None else reason)
    except OSError:
        pass
    return reason
