from tpusystem.ops.attention import attend, causal_mask, dot_product_attention
from tpusystem.ops.moe import MoEMLP, expert_capacity, moe_partition_rules, route_top_k
from tpusystem.ops.ring import (ring_attention, ring_self_attention,
                                ulysses_attention, zigzag_ring_attention)

__all__ = ['attend', 'dot_product_attention', 'causal_mask', 'MoEMLP', 'route_top_k',
           'expert_capacity', 'moe_partition_rules', 'ring_attention',
           'ring_self_attention', 'ulysses_attention', 'zigzag_ring_attention']
