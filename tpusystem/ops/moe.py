"""Mixture-of-experts — expert parallelism over the ``expert`` mesh axis.

The reference has only a dense MLP (SURVEY.md §2.4: "EP/MoE | absent");
this module supplies the TPU-native design: experts live as one stacked
weight tensor with a leading ``experts`` dimension sharded over the
``expert`` mesh axis. Token routing has two formulations behind one layer:

* **sparse**: sort/segment dispatch — a stable argsort by expert id
  gives each assignment its position-in-expert, and scatter/gather moves
  only the O(tokens·k) selected rows (the dense tensors are
  O(tokens·experts·capacity) ≈ O(tokens²·k) in memory and FLOPs).
  Single-shard row movement has three implementations behind
  ``sparse_impl``: ``'scatter'`` (row scatter/scatter-add), ``'gather'``
  (scatter-free custom_vjp pair) and ``'fused'`` (megablocks-style
  Pallas grouped gather-matmul — the rows never make a standalone HBM
  round trip at all; see :func:`_fused_moe`).
  Single-shard it runs directly; on multi-device meshes it runs inside
  ``shard_map`` with token rows sharded over (data, fsdp, seq, expert)
  and a regular differentiable ``all_to_all`` carrying each sender's
  fixed per-expert quota to the expert's owner — SURVEY §2.4's
  ragged-style exchange, made static-shaped by quota padding.
* **dense**: one-hot dispatch/combine einsums (the Switch/GSPMD
  formulation); the partitioner shards them freely and inserts the
  collectives itself. ``dispatch='auto'`` falls back here when the
  sharded-sparse preconditions fail (indivisible rows/experts, model-axis
  TP inside experts).

Capacity model: each expert processes at most
``capacity = round(k * tokens / experts * capacity_factor)`` tokens per
batch; overflow tokens fall through the residual connection (standard
drop-token semantics). Router runs in float32 with a load-balance loss
(Switch eq. 4) plus a router z-loss for logit stability; the layer returns
``(output, aux_loss)`` and :class:`tpusystem.train.losses.WithAuxLoss`
folds the aux term into any base criterion.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.parallel.mesh import EXPERT, axis_size, shard_map


def _ragged_transport(transport: str, axis: str, operand, out_init,
                      in_off, send_sz, out_off, recv_sz):
    """One ragged exchange over ``axis``: chunk ``d`` of ``operand``
    (``[in_off[d], in_off[d] + send_sz[d])``) lands on device ``d`` at
    offset ``out_off[d]`` of its ``out_init``-shaped buffer.

    ``transport='ragged'`` is ``jax.lax.ragged_all_to_all`` — bytes on the
    wire are the *actual* routed rows. ``'gathered'`` is a semantically
    identical emulation (all_gather + masked slice) for backends whose XLA
    has no ragged-all-to-all lowering (CPU, incl. the virtual test meshes);
    it moves more bytes but seats identically, so tests pin the semantics
    the TPU transport then inherits.
    """
    if transport == 'ragged':
        return lax.ragged_all_to_all(operand, out_init, in_off, send_sz,
                                     out_off, recv_sz, axis_name=axis)
    if transport != 'gathered':
        raise ValueError(f'unknown ragged transport {transport!r}')
    n = axis_size(axis)
    me = lax.axis_index(axis)
    all_ops = lax.all_gather(operand, axis)              # [n, S, cols]
    all_in_off = lax.all_gather(in_off, axis)            # [n, n]
    all_send = lax.all_gather(send_sz, axis)
    all_out_off = lax.all_gather(out_off, axis)
    out = out_init
    rows = jnp.arange(out_init.shape[0])
    for sender in range(n):
        src_off = all_in_off[sender, me]
        size = all_send[sender, me]
        dst_off = all_out_off[sender, me]
        take = jnp.clip(rows - dst_off + src_off, 0, operand.shape[0] - 1)
        values = jnp.take(all_ops[sender], take, axis=0)
        mask = (rows >= dst_off) & (rows < dst_off + size)
        out = jnp.where(mask[:, None], values, out)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ragged_exchange(transport, axis, operand, out_init, in_off, send_sz,
                     out_off, recv_off, recv_sz, rev_out_off):
    """Differentiable ragged exchange.

    ``ragged_all_to_all`` has no transpose rule in XLA, so the backward is
    supplied explicitly (ROADMAP: custom_vjp for the reverse exchange): the
    cotangent of the output is exchanged *back* with the send/recv roles
    swapped — my received chunks (``recv_off``/``recv_sz``) return to their
    senders, landing at the positions they were sent from
    (``rev_out_off[d]`` = the offset device ``d`` used for me, i.e. its
    ``in_off[me]``).
    """
    return _ragged_transport(transport, axis, operand, out_init,
                             in_off, send_sz, out_off, recv_sz)


def _ragged_exchange_fwd(transport, axis, operand, out_init, in_off, send_sz,
                         out_off, recv_off, recv_sz, rev_out_off):
    out = _ragged_transport(transport, axis, operand, out_init,
                            in_off, send_sz, out_off, recv_sz)
    residuals = (in_off, send_sz, recv_off, recv_sz, rev_out_off,
                 operand.shape, out_init.shape)
    return out, residuals


def _ragged_exchange_bwd(transport, axis, residuals, cot):
    in_off, send_sz, recv_off, recv_sz, rev_out_off, op_shape, out_shape = residuals
    # reverse roles: my received chunks carry the cotangent home
    d_operand = _ragged_transport(
        transport, axis, cot, jnp.zeros(op_shape, cot.dtype),
        recv_off, recv_sz, rev_out_off, send_sz)
    # out_init passes through wherever nothing was received
    rows = jnp.arange(out_shape[0])
    received = jnp.zeros((out_shape[0],), bool)
    for sender in range(recv_off.shape[0]):
        received = received | ((rows >= recv_off[sender])
                               & (rows < recv_off[sender] + recv_sz[sender]))
    d_init = jnp.where(received[:, None], 0, cot)
    f0 = lambda arr: np.zeros(arr.shape, jax.dtypes.float0)
    return (d_operand, d_init, f0(in_off), f0(send_sz), f0(send_sz),
            f0(recv_off), f0(recv_sz), f0(rev_out_off))


_ragged_exchange.defvjp(_ragged_exchange_fwd, _ragged_exchange_bwd)


def expert_capacity(tokens: int, experts: int, k: int,
                    capacity_factor: float) -> int:
    """Per-expert token budget (at least 1, at most all tokens)."""
    return max(1, min(tokens, int(tokens * k * capacity_factor / experts)))


def route_top_k(gates: jax.Array, k: int, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    Args:
        gates: [tokens, experts] router probabilities (float32).
        k: choices per token; chosen gates renormalize to sum to 1.
        capacity: per-expert slot budget.

    Returns:
        dispatch: [tokens, experts, capacity] 0/1 routing tensor.
        combine: same shape, dispatch weighted by the (renormalized) gate.
        fraction: [experts] fraction of tokens whose *first* choice was the
            expert (the load-balance loss term).

    Slots are granted choice-major: every token's first choice is seated
    before any second choice, and within a choice in token order — so drop
    behavior is deterministic and first choices always win over overflow.
    """
    tokens, experts = gates.shape
    top_gates, top_experts = jax.lax.top_k(gates, k)
    top_gates = top_gates / (jnp.sum(top_gates, -1, keepdims=True) + 1e-9)

    dispatch = jnp.zeros((tokens, experts, capacity), jnp.float32)
    combine = jnp.zeros((tokens, experts, capacity), jnp.float32)
    seated = jnp.zeros((experts,), jnp.float32)
    for choice in range(k):
        onehot = jax.nn.one_hot(top_experts[:, choice], experts)  # [N, E]
        position = jnp.cumsum(onehot, axis=0) - 1 + seated
        seated = seated + jnp.sum(onehot, axis=0)
        fits = (position < capacity) * onehot
        slot = jax.nn.one_hot(position.astype(jnp.int32), capacity)  # [N, E, C]
        placed = fits[:, :, None] * slot
        dispatch = dispatch + placed
        combine = combine + placed * top_gates[:, choice][:, None, None]
    first_choice = jax.nn.one_hot(top_experts[:, 0], experts)
    fraction = jnp.mean(first_choice, axis=0)
    return dispatch, combine, fraction


def _seating_positions(keys: jax.Array, length: int):
    """Rank each element among equals: position-in-group via one stable
    argsort plus a scatter-inverted permutation.

    ``keys`` are small non-negative integers (< ``length``); returns each
    element's 0-based position among the elements sharing its key, in
    stable (input) order — the seating primitive behind every sparse
    dispatch path (sender compaction, receiver capacity, slot assignment),
    kept single so the seating-order invariant cannot drift between them.
    """
    order = jnp.argsort(keys, stable=True)
    # invert the permutation with one scatter (a second argsort is O(n log n))
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(order.size))
    counts = jnp.bincount(keys, length=length)
    starts = jnp.cumsum(counts) - counts
    return ranks - starts[keys], counts


def route_top_k_sparse(gates: jax.Array, k: int, capacity: int):
    """Sort-based routing: the O(tokens·k) replacement for the dense
    [tokens, experts, capacity] one-hot tensors (SURVEY §2.4 mandates
    ragged-style dispatch; the dense einsums are an O(tokens²)·k FLOP and
    memory cliff at real expert counts).

    Returns ``(token_ids, slots, weights, fraction)`` flat per-assignment
    arrays (length ``tokens*k``): assignment ``i`` sends token
    ``token_ids[i]`` to buffer row ``slots[i]`` (``experts*capacity`` means
    dropped — scatter/gather with ``mode='drop'``/``fill`` discards it) and
    its output is combined back with ``weights[i]``.

    Seating matches :func:`route_top_k` exactly: assignments are flattened
    choice-major and position-in-expert comes from a *stable* sort by
    expert id, so every first choice seats before any second choice and
    within a choice tokens seat in order.
    """
    tokens, experts = gates.shape
    top_gates, top_experts = jax.lax.top_k(gates, k)
    top_gates = top_gates / (jnp.sum(top_gates, -1, keepdims=True) + 1e-9)

    expert_ids = top_experts.T.reshape(-1)             # [k*N] choice-major
    weights = top_gates.T.reshape(-1)
    token_ids = jnp.tile(jnp.arange(tokens), k)

    position, _ = _seating_positions(expert_ids, experts)
    keep = position < capacity
    slots = jnp.where(keep, expert_ids * capacity + position,
                      experts * capacity)              # out of range = dropped

    fraction = jnp.mean(jax.nn.one_hot(top_experts[:, 0], experts), axis=0)
    return token_ids, slots, weights, fraction


def _invert_seating(slots, k: int, tokens: int, buffer_rows: int):
    """Invert the choice-major seating once in integer space (the only
    scatter in the gather impl — ``buffer_rows`` int32 elements): buffer
    row -> assignment (``slot_asg``, ``k*tokens`` sentinel for empty),
    buffer row -> token (``slot_token``, ``tokens`` sentinel;
    ``token_ids[a] = a % tokens`` by route_top_k_sparse's choice-major
    layout), and the per-choice ``[k, tokens]`` view of ``slots``. Shared
    with benchmarks/moe_ceiling.py so the benchmark measures exactly the
    dispatch MoEMLP executes."""
    assignments = k * tokens
    slot_asg = jnp.full((buffer_rows,), assignments,
                        jnp.int32).at[slots].set(
        jnp.arange(assignments, dtype=jnp.int32), mode='drop')
    slot_token = jnp.where(slot_asg < assignments, slot_asg % tokens, tokens)
    return slot_asg, slot_token, slots.reshape(k, tokens)


@jax.custom_vjp
def _gather_dispatch(flat, slot_token, slots_by_choice):
    """Scatter-free expert-buffer fill: ``buffer[j] = flat[slot_token[j]]``.

    ``slot_token`` maps each of the ``experts*capacity`` buffer rows to
    its token (``tokens`` = out-of-range for empty slots, so the gather's
    ``fill_value=0`` zeroes them); ``slots_by_choice`` is ``[k, tokens]``
    buffer rows per (choice, token) (``experts*capacity`` when dropped),
    used only by the backward. Both directions lower to *gathers* plus a
    k-way sum — on TPU the row-scatter formulation
    (``buffer.at[slots].set(rows)``) pays the scatter lowering in the
    forward AND a scatter-add transpose in the backward; this is the
    same class of fix as round 4's decode cache write (14x)."""
    return flat.at[slot_token].get(mode='fill', fill_value=0)


def _gather_dispatch_fwd(flat, slot_token, slots_by_choice):
    out = _gather_dispatch(flat, slot_token, slots_by_choice)
    return out, (slot_token, slots_by_choice)


def _gather_dispatch_bwd(residuals, d_buffer):
    slot_token, slots_by_choice = residuals
    # d_flat[t] = sum over t's seated choices of d_buffer at that slot:
    # k gathers (OOB rows of dropped assignments fill 0) + a k-way sum
    d_flat = sum(d_buffer.at[slots_by_choice[c]].get(mode='fill',
                                                     fill_value=0)
                 for c in range(slots_by_choice.shape[0]))
    zero = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return d_flat, zero(slot_token), zero(slots_by_choice)


_gather_dispatch.defvjp(_gather_dispatch_fwd, _gather_dispatch_bwd)


@jax.custom_vjp
def _gather_combine(buffer, weights, slots_by_choice, slot_token, slot_asg):
    """Scatter-free combine: ``out[t] = sum_c w[c,t] * buffer[slot(c,t)]``.

    Replaces the gather + ``at[token_ids].add`` scatter-add of the
    scatter formulation: ``token_ids`` is ``tile(arange(tokens), k)`` by
    construction (route_top_k_sparse flattens choice-major), so the
    scatter-add over it IS a reshape-to-[k, tokens]-and-sum — expressed
    directly here. Backward: ``d_buffer`` gathers ``d_out`` by
    ``slot_token`` weighted by the per-slot gate (``weights[slot_asg]``),
    ``d_weights`` is a rowwise dot of the re-gathered buffer rows with
    ``d_out`` — gathers only, no scatter in either direction."""
    k = slots_by_choice.shape[0]
    compute = buffer.dtype
    out = None
    for c in range(k):
        gathered = buffer.at[slots_by_choice[c]].get(mode='fill',
                                                     fill_value=0)
        w = weights.reshape(k, -1)[c][:, None].astype(compute)
        out = gathered * w if out is None else out + gathered * w
    return out


def _gather_combine_fwd(buffer, weights, slots_by_choice, slot_token,
                        slot_asg):
    out = _gather_combine(buffer, weights, slots_by_choice, slot_token,
                          slot_asg)
    return out, (buffer, weights, slots_by_choice, slot_token, slot_asg)


def _combine_bwd_terms(buffer, weights, slots_by_choice, slot_token,
                       slot_asg, d_out, compute):
    """The weighted-combine backward, shared by the gather impl and the
    fused impl so their numerics cannot drift (tests pin them against
    each other): ``d_buffer`` gathers the output cotangent by
    ``slot_token`` scaled by the per-slot gate (compute dtype, empty
    slots fill 0); ``d_weights`` is the choice-major concat of f32
    rowwise dots of the re-gathered buffer rows with ``d_out``."""
    w_slot = weights.at[slot_asg].get(mode='fill', fill_value=0)
    d_buffer = (w_slot[:, None].astype(compute)
                * d_out.at[slot_token].get(mode='fill', fill_value=0))
    d_w = []
    for c in range(slots_by_choice.shape[0]):
        gathered = buffer.at[slots_by_choice[c]].get(mode='fill',
                                                     fill_value=0)
        d_w.append(jnp.sum(gathered.astype(jnp.float32)
                           * d_out.astype(jnp.float32), axis=-1))
    return d_buffer, jnp.concatenate(d_w).astype(weights.dtype)


def _gather_combine_bwd(residuals, d_out):
    buffer, weights, slots_by_choice, slot_token, slot_asg = residuals
    d_buffer, d_weights = _combine_bwd_terms(
        buffer, weights, slots_by_choice, slot_token, slot_asg, d_out,
        buffer.dtype)
    zero = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (d_buffer, d_weights, zero(slots_by_choice), zero(slot_token),
            zero(slot_asg))


_gather_combine.defvjp(_gather_combine_fwd, _gather_combine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_moe(config, flat, w1, b1, w2, b2, weights, slot_token, slot_asg,
               slots_by_choice):
    """Megablocks-style fused sparse MoE: dispatch rides the first expert
    matmul's loads, the k-way weighted combine rides the second's epilogue.

    Two Pallas grouped-matmul kernels
    (:mod:`tpusystem.ops.pallas.grouped_matmul`) replace the
    dispatch/FFN/combine pipeline: :func:`gather_rows_matmul` DMAs token
    rows from the *unpermuted* ``flat`` straight into the up-projection's
    MXU tiles (the ``[experts*capacity, dim]`` dispatch buffer is never
    materialized), and :func:`matmul_scatter_rows` accumulates each
    down-projected row onto its token's output row, scaled by its combine
    weight, in the matmul's epilogue (no buffer-order result is gathered
    back). The backward reuses the SAME kernels with swapped operands —
    ``d_buffer`` gather-matmuls the output cotangent against w2^T with the
    combine weights as the per-row scale, ``d_flat`` matmul-scatters the
    hidden cotangent against w1^T — with f32 MXU accumulation matching the
    gather impl's numerics class (parity is tolerance-bounded, not
    bitwise: summation orders differ).

    ``config`` is ``(capacity, interpret)`` — static; ``interpret=None``
    auto-selects interpreter mode off-TPU so CPU tests run the kernels.
    Integer seating arrays ride as differentiable args returning float0
    (the repo's custom_vjp convention). All float operands arrive in the
    compute dtype; master-weight casts live in the caller.
    """
    out, _ = _fused_moe_fwd(config, flat, w1, b1, w2, b2, weights,
                            slot_token, slot_asg, slots_by_choice)
    return out


def _fused_moe_fwd(config, flat, w1, b1, w2, b2, weights, slot_token,
                   slot_asg, slots_by_choice):
    from tpusystem.ops.pallas.grouped_matmul import (gather_rows_matmul,
                                                     matmul_scatter_rows)
    capacity, interpret = config
    tokens = flat.shape[0]
    experts = w1.shape[0]
    clamped = jnp.minimum(slot_token, tokens - 1)
    valid = (slot_token < tokens).astype(jnp.float32)
    # per-slot combine weight; empty slots (sentinel slot_asg) fill 0
    w_slot = weights.at[slot_asg].get(mode='fill', fill_value=0)

    up = gather_rows_matmul(flat, w1, clamped, valid,
                            rows_per_group=capacity, interpret=interpret)
    pre = up.reshape(experts, capacity, -1) + b1[:, None]
    grown = nn.gelu(pre).reshape(experts * capacity, -1)
    out, shrunk = matmul_scatter_rows(grown, w2, b2, slot_token, w_slot,
                                      tokens, rows_per_group=capacity,
                                      interpret=interpret)
    residuals = (flat, w1, b1, w2, b2, weights, slot_token, slot_asg,
                 slots_by_choice, clamped, w_slot, pre, shrunk)
    return out, residuals


def _fused_moe_bwd(config, residuals, d_out):
    from tpusystem.ops.pallas.grouped_matmul import (gather_rows_matmul,
                                                     matmul_scatter_rows)
    (flat, w1, b1, w2, b2, weights, slot_token, slot_asg, slots_by_choice,
     clamped, w_slot, pre, shrunk) = residuals
    capacity, interpret = config
    tokens, compute = flat.shape[0], flat.dtype
    experts = w1.shape[0]
    valid = (slot_token < tokens).astype(jnp.float32)
    grown = nn.gelu(pre)                           # recomputed, VPU-cheap

    # combine backward: the EXACT terms of _gather_combine_bwd, via the
    # shared helper, against the kernel-saved shrunk rows
    d_shrunk, d_weights = _combine_bwd_terms(
        shrunk, weights, slots_by_choice, slot_token, slot_asg, d_out,
        compute)
    d_shrunk3 = d_shrunk.reshape(experts, capacity, -1)
    d_w2 = jnp.einsum('ech,ecd->ehd', grown, d_shrunk3,
                      preferred_element_type=jnp.float32).astype(w2.dtype)
    d_b2 = jnp.sum(d_shrunk3.astype(jnp.float32), axis=1).astype(b2.dtype)

    # same kernel, swapped operands: d_grown[j] = w_slot[j] *
    # d_out[token_j] @ w2[e]^T — the gather rides the matmul again
    d_grown = gather_rows_matmul(d_out, w2, clamped, w_slot,
                                 rows_per_group=capacity,
                                 transpose_rhs=True, interpret=interpret)
    _, gelu_vjp = jax.vjp(nn.gelu, pre)
    (d_pre,) = gelu_vjp(d_grown.reshape(experts, capacity, -1)
                        .astype(pre.dtype))
    d_b1 = jnp.sum(d_pre.astype(jnp.float32), axis=1).astype(b1.dtype)

    # dispatch backward: d_flat[t] = sum of t's seated d_expert_in rows,
    # i.e. the scatter-combine kernel against w1^T with unit weights
    d_flat, _ = matmul_scatter_rows(d_pre.reshape(experts * capacity, -1),
                                    w1, None, slot_token, valid, tokens,
                                    rows_per_group=capacity,
                                    transpose_rhs=True, save_rows=False,
                                    interpret=interpret)
    # d_w1 needs the gathered rows the forward never materialized; one
    # XLA gather rematerializes them (the gather impl's backward pays the
    # same class of traffic)
    expert_in = flat.at[slot_token].get(mode='fill', fill_value=0)
    d_w1 = jnp.einsum('ecd,ech->edh',
                      expert_in.reshape(experts, capacity, -1), d_pre,
                      preferred_element_type=jnp.float32).astype(w1.dtype)

    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (d_flat.astype(flat.dtype), d_w1, d_b1, d_w2, d_b2, d_weights,
            f0(slot_token), f0(slot_asg), f0(slots_by_choice))


_fused_moe.defvjp(_fused_moe_fwd, _fused_moe_bwd)


class MoEMLP(nn.Module):
    """Expert-parallel FFN: drop-in for the dense fc->gelu->proj block.

    Returns ``(output, aux_loss)`` where ``aux_loss`` already carries the
    configured coefficients. Weights are stacked [experts, ...] float32
    masters cast to ``dtype`` per use; pass ``mesh`` to pin the dispatched
    activations to the expert axis (otherwise GSPMD chooses).

    **Drop semantics across dispatch paths** (they agree exactly whenever
    capacity is ample — no drops — which is the recommended operating
    point): the dense and single-shard sparse paths seat tokens in global
    choice-major order (every first choice before any second choice,
    token-major within a choice). On a multi-device mesh the quota'd
    sharded-sparse path (``exchange='quota'``, the ``'auto'``/``'sparse'``
    default) instead decides drops *per sender*: each shard seats its own
    assignments choice-major into a fixed per-expert quota (its
    integer-truncated share of the capacity), so under tight capacity
    *which* tokens overflow differs from the dense path, and a sender with
    a locally-skewed routing drops tokens the global formulation would
    seat. ``exchange='ragged'`` restores receiver-side global-order
    seating within each expert-axis group (and moves only the actual
    routed rows); see its docstring for the remaining cross-group caveat.
    """

    experts: int
    k: int = 2
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    balance_coef: float = 1e-2
    z_coef: float = 1e-3
    mesh: object = None
    dispatch: str = 'auto'   # 'sparse' | 'dense' | 'auto'
    # multi-device sparse exchange: 'quota' ships fixed per-sender quotas
    # through a regular all_to_all (pads to the quota); 'ragged' ships the
    # actual routed rows through jax.lax.ragged_all_to_all with
    # receiver-side global-order capacity seating; 'ragged-emulated' is the
    # same seating semantics over an all_gather transport for backends
    # whose XLA cannot lower ragged-all-to-all (CPU test/virtual meshes)
    exchange: str = 'quota'
    # single-shard sparse data movement: 'gather' routes dispatch+combine
    # through the scatter-free custom_vjp pair (_gather_dispatch /
    # _gather_combine — gathers + k-way sums in both directions, one tiny
    # int scatter to invert the seating); 'scatter' is the row-scatter
    # formulation (the A/B reference; benchmarks/moe_ceiling.py); 'fused'
    # folds dispatch into the up-projection's loads and the weighted
    # combine into the down-projection's epilogue with the Pallas grouped
    # gather-matmul kernels (_fused_moe — megablocks-style; bitwise
    # parity with gather/scatter is NOT expected, only tolerance-bounded:
    # the MXU accumulates in f32 and sums in different orders)
    sparse_impl: str = 'gather'
    # full_capacity: seat EVERY assignment — capacity = tokens, the
    # ample-capacity operating point made unconditional. With no drops
    # each token's expert mix depends only on that token, so outputs are
    # independent of co-batched traffic and of pad-bucket width — the
    # property the serving engine's shared-batch decode step needs for
    # token-exactness (and what lifts its MoE gate). Decode clones set
    # it (models.gpt2.Block passes full_capacity=decode); training keeps
    # the capacity_factor economics. Governs the single-shard paths —
    # decode clones reset mesh=None, so decode always lands there.
    full_capacity: bool = False
    # schedule: parallel.OverlapSchedule — its moe= arm governs the
    # sharded quota dispatch. moe='overlap' splits the local token rows
    # into microbatch pieces and software-pipelines the exchange: piece
    # k+1's dispatch all_to_all issues UNDER the expert matmuls of piece
    # k, and piece k's return exchange rides under the matmuls of k+1 —
    # the expert a2a leaves the critical path the way the TP/FSDP rings
    # did. Pure moe_plan (parallel/schedule.py) pins the one-shot
    # fallback (ragged exchanges, rows that won't split); None or
    # moe='gspmd' keeps the single whole-batch exchange. Routing runs on
    # the full local rows either way (aux losses bitwise-invariant); per-
    # piece quotas are the quota path's per-sender drop discipline at
    # finer grain — with ample capacity (no drops) outputs are bitwise-
    # equal to the one-shot path
    schedule: object = None

    @nn.compact
    def __call__(self, hidden):
        batch_shape, dim = hidden.shape[:-1], hidden.shape[-1]
        hidden_dim = self.mlp_ratio * dim
        flat = hidden.reshape(-1, dim)
        tokens = flat.shape[0]

        router = self.param('router', nn.initializers.normal(0.02),
                            (dim, self.experts), jnp.float32)
        init = nn.initializers.lecun_normal()
        w1 = self.param('w1', init, (self.experts, dim, hidden_dim), jnp.float32)
        b1 = self.param('b1', nn.initializers.zeros, (self.experts, hidden_dim), jnp.float32)
        w2 = self.param('w2', init, (self.experts, hidden_dim, dim), jnp.float32)
        b2 = self.param('b2', nn.initializers.zeros, (self.experts, dim), jnp.float32)

        # 'sparse' is the O(tokens·k) sort/scatter path. Single-shard it
        # runs directly; on a multi-device mesh it runs inside shard_map
        # with token rows sharded over (data, fsdp, expert) and a regular
        # all_to_all moving each sender's per-expert quota to the expert's
        # owner (_sharded_sparse — SURVEY §2.4's ragged-style dispatch,
        # made exchangeable with static shapes by fixed per-sender
        # quotas). 'auto' falls back to the dense one-hot einsums when the
        # sharded preconditions don't hold (divisibility, unsharded model
        # axis); explicit 'sparse' raises instead of silently degrading.
        if self.sparse_impl not in ('gather', 'scatter', 'fused'):
            raise ValueError(f'unknown sparse_impl {self.sparse_impl!r}; '
                             "expected 'gather', 'scatter' or 'fused'")
        mode = self.dispatch
        if mode == 'auto':
            if self.mesh is None or self.mesh.size == 1:
                mode = 'sparse'
            else:
                problem = self._sharded_sparse_blocker(tokens)
                mode = 'dense' if problem else 'sparse_sharded'
        elif mode == 'sparse':
            if self.mesh is not None and self.mesh.size > 1:
                problem = self._sharded_sparse_blocker(tokens)
                if problem:
                    raise ValueError(
                        f'dispatch=sparse on a multi-device mesh: {problem} '
                        f"(use dispatch='auto' to fall back to dense)")
                mode = 'sparse_sharded'
        elif mode != 'dense':
            raise ValueError(f'unknown dispatch {self.dispatch!r}; '
                             "expected 'sparse', 'dense' or 'auto'")
        compute = jnp.dtype(self.dtype)

        if mode == 'sparse_sharded':
            if self.sparse_impl == 'fused' and self.dispatch == 'sparse':
                # the sharded formulations own their row movement (quota /
                # ragged exchanges); the fused kernels are single-shard
                # today. An EXPLICIT dispatch='sparse' raises rather than
                # silently running a different impl (the repo contract);
                # dispatch='auto' keeps its no-raise promise and proceeds
                # with the sharded formulation.
                raise ValueError(
                    "sparse_impl='fused' is single-shard only; on a "
                    'multi-device mesh the sharded sparse path uses its '
                    "exchange formulation (see exchange=). Use "
                    "sparse_impl='gather' there, or dispatch='auto' to "
                    'accept the sharded formulation.')
            if self.exchange in ('ragged', 'ragged-emulated'):
                output, aux = self._sharded_ragged(flat, router, w1, b1, w2,
                                                   b2, compute)
            elif self.exchange == 'quota':
                output, aux = self._sharded_sparse(flat, router, w1, b1, w2,
                                                   b2, compute)
            else:
                raise ValueError(f'unknown exchange {self.exchange!r}; '
                                 "expected 'quota', 'ragged' or "
                                 "'ragged-emulated'")
            return output.reshape(*batch_shape, dim).astype(hidden.dtype), aux

        logits = flat.astype(jnp.float32) @ router
        gates = jax.nn.softmax(logits)
        capacity = (tokens if self.full_capacity
                    else expert_capacity(tokens, self.experts, self.k,
                                         self.capacity_factor))

        if mode == 'sparse':
            token_ids, slots, weights, fraction = route_top_k_sparse(
                gates, self.k, capacity)
        else:
            dispatch, combine, fraction = route_top_k(gates, self.k, capacity)

        # Switch load-balance loss: experts * <fraction_dispatched * mean_prob>
        balance = self.experts * jnp.sum(fraction * jnp.mean(gates, axis=0))
        z_term = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        aux = self.balance_coef * balance + self.z_coef * z_term

        if mode == 'sparse' and self.sparse_impl in ('gather', 'fused'):
            # ONE seating inversion serves both impls — the parity their
            # tests pin depends on them reading identical slot maps
            slot_asg, slot_token, slots_by_choice = _invert_seating(
                slots, self.k, tokens, self.experts * capacity)

        if mode == 'sparse' and self.sparse_impl == 'fused':
            # megablocks-style: both data movements ride the expert
            # matmuls (Pallas grouped gather-matmul / matmul-scatter);
            # no dispatch buffer, no combine gather — see _fused_moe
            output = _fused_moe(
                (capacity, None), flat.astype(compute), w1.astype(compute),
                b1.astype(compute), w2.astype(compute), b2.astype(compute),
                weights, slot_token, slot_asg, slots_by_choice)
            return output.reshape(*batch_shape, dim).astype(hidden.dtype), aux

        if mode == 'sparse':
            if self.sparse_impl == 'gather':
                expert_in = _gather_dispatch(flat.astype(compute),
                                             slot_token, slots_by_choice)
            else:
                rows = flat.astype(compute)[token_ids]     # [k*N, D] gather
                expert_in = jnp.zeros((self.experts * capacity, dim), compute)
                expert_in = expert_in.at[slots].set(rows, mode='drop')
            expert_in = expert_in.reshape(self.experts, capacity, dim)
        else:
            expert_in = jnp.einsum('nec,nd->ecd', dispatch.astype(compute),
                                   flat.astype(compute))

        expert_in = self._constrain(expert_in)
        shrunk = self._ffn(expert_in, w1, b1, w2, b2, compute)
        shrunk = self._constrain(shrunk)

        if mode == 'sparse':
            buffer = shrunk.reshape(self.experts * capacity, dim)
            if self.sparse_impl == 'gather':
                output = _gather_combine(buffer, weights, slots_by_choice,
                                         slot_token, slot_asg)
            else:
                output = self._sparse_combine(buffer, slots, token_ids,
                                              weights, tokens, dim, compute)
        else:
            output = jnp.einsum('nec,ecd->nd', combine.astype(compute), shrunk)
        return output.reshape(*batch_shape, dim).astype(hidden.dtype), aux

    def _ffn(self, expert_in, w1, b1, w2, b2, compute):
        """The per-expert MLP — one implementation for every dispatch path,
        so the parity the tests pin cannot drift."""
        grown = jnp.einsum('ecd,edh->ech', expert_in, w1.astype(compute))
        grown = nn.gelu(grown + b1[:, None].astype(compute))
        return (jnp.einsum('ech,ehd->ecd', grown, w2.astype(compute))
                + b2[:, None].astype(compute))

    @staticmethod
    def _sparse_combine(buffer, slots, token_ids, weights, tokens, dim,
                        compute):
        gathered = buffer.at[slots].get(mode='fill', fill_value=0)
        return jnp.zeros((tokens, dim), compute).at[token_ids].add(
            gathered * weights[:, None].astype(compute))

    def _constrain(self, value):
        from tpusystem.parallel.sharding import constrain_expert_major
        return constrain_expert_major(value, self.mesh)

    def _sharded_sparse_blocker(self, tokens: int) -> str | None:
        """Why the sharded sparse path cannot run (None = it can)."""
        from tpusystem.parallel.mesh import DATA, FSDP, MODEL, SEQ
        shape = dict(self.mesh.shape)
        # the dispatch shard_map names all four row axes in its specs, so a
        # hand-built mesh missing any of them must fall back to dense
        # instead of raising a KeyError mid-trace
        missing = [axis for axis in (DATA, FSDP, SEQ, EXPERT)
                   if axis not in shape]
        if missing:
            return (f'mesh lacks the standard row axes {missing} the '
                    'sparse dispatch shards over')
        shards = (shape.get(DATA, 1) * shape.get(FSDP, 1)
                  * shape.get(SEQ, 1) * shape.get(EXPERT, 1))
        if shape.get(MODEL, 1) > 1:
            return 'model-axis TP inside experts is dense-only'
        if self.experts % shape.get(EXPERT, 1):
            return (f'{self.experts} experts not divisible by the expert '
                    f'axis ({shape.get(EXPERT, 1)})')
        if tokens % shards:
            return (f'{tokens} token rows not divisible by '
                    f'data*fsdp*seq*expert = {shards}')
        return None

    def _sharded_sparse(self, flat, router, w1, b1, w2, b2, compute):
        """Expert-parallel sparse dispatch inside ``shard_map``.

        Token rows shard over (data, fsdp, expert); each device seats its
        assignments into a ``[experts, quota]`` send buffer with
        :func:`route_top_k_sparse` (quota = its share of the global
        capacity), one **regular** ``all_to_all`` over the expert axis
        hands every expert's rows to its owner, the FFN runs on
        ``[local_experts, senders*quota]`` seated rows (no receiver-side
        sort), and the inverse exchange brings outputs home for the
        weighted combine. Fixed per-sender quotas are what make the
        exchange static-shaped — the ragged-a2a formulation SURVEY §2.4
        calls for, with padding instead of raggedness; ``all_to_all``
        differentiates (its transpose is the reverse exchange), so the
        whole path trains. Capacity semantics differ from the dense path:
        drops are decided per sender (choice-major within each shard), not
        by global token order — with ample capacity (no drops) the two
        paths agree exactly.

        With ``schedule.moe='overlap'``
        (:class:`~tpusystem.parallel.schedule.OverlapSchedule`, planned by
        the pure :func:`~tpusystem.parallel.schedule.moe_plan`) the local
        rows split into microbatch pieces and the exchanges software-
        pipeline: piece ``k+1``'s dispatch ``all_to_all`` is issued
        *before* piece ``k``'s expert matmuls in program order — the two
        are dataflow-independent, so the transfer hides under the MXU
        work — and piece ``k``'s return exchange rides under the matmuls
        of ``k+1`` the same way. Routing runs on the full local rows
        first (router logits/gates and the aux losses are bitwise
        identical to the one-shot path); each piece seats into its own
        per-piece quota (the per-sender drop discipline at finer grain:
        with ample capacity, outputs are bitwise-equal to one-shot).
        """
        import functools

        from jax import lax

        from tpusystem.parallel.mesh import DATA, FSDP, SEQ
        from tpusystem.parallel.schedule import MoePlan, moe_plan

        mesh = self.mesh
        expert_ax = mesh.shape[EXPERT]
        local_experts = self.experts // expert_ax
        shards = (mesh.shape[DATA] * mesh.shape[FSDP] * mesh.shape[SEQ]
                  * expert_ax)
        local_rows = flat.shape[0] // shards
        # clamp like expert_capacity: a sender cannot route more than its
        # local_rows assignments to any one expert, so a larger quota only
        # pads the all_to_all buffers with unreachable zero rows
        quota = max(1, min(local_rows,
                           int(local_rows * self.k * self.capacity_factor
                               / self.experts)))
        dim = flat.shape[1]
        experts, k = self.experts, self.k
        capacity_factor = self.capacity_factor
        row_axes = (DATA, FSDP, SEQ, EXPERT)
        row_spec = P(row_axes, None)
        if (self.schedule is not None
                and getattr(self.schedule, 'moe', 'gspmd') == 'overlap'):
            plan = moe_plan(local_rows, expert_ax, self.exchange)
        else:
            plan = MoePlan('one-shot', 1, 'moe overlap inactive')

        def exchange(buffer):
            # chunk d of a send buffer (global expert order, owners
            # contiguous) goes to device d; twice the same tiled exchange
            # is the identity, which is how outputs come home
            return lax.all_to_all(buffer, EXPERT, split_axis=0,
                                  concat_axis=0, tiled=True)

        def seat(rows_piece, gates_piece, piece_quota):
            """Route one piece into its [experts * piece_quota, dim] send
            buffer (choice-major per-sender seating — the path's one drop
            discipline, at the piece's own quota)."""
            token_ids, slots, weights, _ = route_top_k_sparse(
                gates_piece, k, piece_quota)
            send = jnp.zeros((experts * piece_quota, dim), compute)
            send = send.at[slots].set(rows_piece.astype(compute)[token_ids],
                                      mode='drop')
            return send, (slots, token_ids, weights)

        def expert_pass(recv, piece_quota, w1, b1, w2, b2):
            """Seated arrivals -> expert FFN -> buffer-order returns."""
            expert_in = (recv.reshape(expert_ax, local_experts,
                                      piece_quota, dim)
                         .transpose(1, 0, 2, 3)
                         .reshape(local_experts, expert_ax * piece_quota,
                                  dim))
            shrunk = self._ffn(expert_in, w1, b1, w2, b2, compute)
            return (shrunk.reshape(local_experts, expert_ax, piece_quota,
                                   dim)
                    .transpose(1, 0, 2, 3)
                    .reshape(experts * piece_quota, dim))

        @functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=(row_spec, P(), P(EXPERT, None, None), P(EXPERT, None),
                      P(EXPERT, None, None), P(EXPERT, None)),
            out_specs=(row_spec, P()))
        def run(rows, router, w1, b1, w2, b2):
            # routing always runs on the FULL local rows: one logits
            # matmul, bitwise-identical gates and aux losses under either
            # dispatch schedule — only the seating/exchange is per-piece
            logits = rows.astype(jnp.float32) @ router
            gates = jax.nn.softmax(logits)

            if plan.path == 'overlap':
                pieces = plan.pieces
                piece_rows = rows.shape[0] // pieces
                piece_quota = max(1, min(piece_rows,
                                         int(piece_rows * k
                                             * capacity_factor / experts)))
                routed = [
                    seat(lax.dynamic_slice_in_dim(rows, p * piece_rows,
                                                  piece_rows),
                         lax.dynamic_slice_in_dim(gates, p * piece_rows,
                                                  piece_rows),
                         piece_quota)
                    for p in range(pieces)]
                # the software pipeline: piece p+1's dispatch a2a issues
                # BEFORE piece p's expert matmuls (independent, so the
                # transfer hides under the MXU work); piece p's return
                # a2a issues after its matmuls and completes under p+1's
                recv = [None] * pieces
                recv[0] = exchange(routed[0][0])
                outs = []
                for p in range(pieces):
                    if p + 1 < pieces:
                        recv[p + 1] = exchange(routed[p + 1][0])
                    back = expert_pass(recv[p], piece_quota, w1, b1, w2, b2)
                    buffer = exchange(back)
                    slots, token_ids, weights = routed[p][1]
                    outs.append(self._sparse_combine(
                        buffer, slots, token_ids, weights, piece_rows, dim,
                        compute))
                output = jnp.concatenate(outs, axis=0)
                # the load-balance fraction, exactly as route_top_k_sparse
                # computes it, from the full gates
                _, top_experts = jax.lax.top_k(gates, k)
                fraction = jnp.mean(jax.nn.one_hot(top_experts[:, 0],
                                                   experts), axis=0)
            else:
                token_ids, slots, weights, fraction = route_top_k_sparse(
                    gates, k, quota)
                send = jnp.zeros((experts * quota, dim), compute)
                send = send.at[slots].set(rows.astype(compute)[token_ids],
                                          mode='drop')
                buffer = exchange(expert_pass(exchange(send), quota,
                                              w1, b1, w2, b2))
                output = self._sparse_combine(buffer, slots, token_ids,
                                              weights, rows.shape[0], dim,
                                              compute)

            # Switch balance/z losses over GLOBAL token statistics
            fraction = lax.pmean(fraction, row_axes)
            mean_gates = lax.pmean(jnp.mean(gates, axis=0), row_axes)
            balance = experts * jnp.sum(fraction * mean_gates)
            z_term = lax.pmean(
                jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2), row_axes)
            aux = self.balance_coef * balance + self.z_coef * z_term
            return output, aux

        return run(flat, router, w1, b1, w2, b2)

    def _sharded_ragged(self, flat, router, w1, b1, w2, b2, compute):
        """Expert-parallel sparse dispatch with a **ragged** exchange.

        Differences from :meth:`_sharded_sparse` (the quota path):

        * the exchange ships the *actual* routed rows —
          ``jax.lax.ragged_all_to_all`` with per-destination offsets/sizes
          (``exchange='ragged'``) or the all_gather emulation with
          identical seating (``'ragged-emulated'``, for backends whose XLA
          cannot lower the primitive) — instead of padding every sender to
          a fixed per-expert quota; under balanced routing at capacity
          factor ``c`` the quota path moves ``~c``x the bytes of this one.
        * capacity is enforced at the **receiver** in global
          ``(choice, token)`` order within the expert-axis group: every
          row travels with a routing key, the expert's owner sorts its
          arrivals and seats the first ``capacity`` — so a sender with
          locally-skewed routing can fill seats the quota path would have
          dropped (its fixed share) while another sender's quota sat
          empty. Remaining divergence from the dense path: competition is
          per expert-axis *group* (the data/fsdp/seq replicas of the
          expert weights each seat their own token subset against a
          proportional ``capacity``), so with drops the seated set matches
          dense only when routing pressure is uniform across groups; with
          ample capacity all paths agree exactly.
        * a sender caps its per-expert sends at ``min(local_rows,
          capacity)`` — rows beyond that could never seat anywhere, since
          a sender's own assignments to one expert are already in global
          order.

        Both exchanges differentiate through :func:`_ragged_exchange`
        (custom_vjp; the reverse exchange carries the cotangent home).
        """
        from tpusystem.parallel.mesh import DATA, FSDP, SEQ

        mesh = self.mesh
        expert_ax = mesh.shape[EXPERT]
        local_experts = self.experts // expert_ax
        shards = (mesh.shape[DATA] * mesh.shape[FSDP] * mesh.shape[SEQ]
                  * expert_ax)
        local_rows = flat.shape[0] // shards
        dim = flat.shape[1]
        experts, k = self.experts, self.k
        group_tokens = local_rows * expert_ax
        capacity = expert_capacity(group_tokens, experts, k,
                                   self.capacity_factor)
        send_cap = min(local_rows, capacity)
        send_bound = min(local_rows * k, experts * send_cap)
        recv_bound = expert_ax * local_experts * send_cap
        key_span = k * expert_ax * local_rows
        transport = 'ragged' if self.exchange == 'ragged' else 'gathered'
        row_axes = (DATA, FSDP, SEQ, EXPERT)
        row_spec = P(row_axes, None)

        @functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=(row_spec, P(), P(EXPERT, None, None), P(EXPERT, None),
                      P(EXPERT, None, None), P(EXPERT, None)),
            out_specs=(row_spec, P()))
        def run(rows, router, w1, b1, w2, b2):
            me = lax.axis_index(EXPERT)
            logits = rows.astype(jnp.float32) @ router
            gates = jax.nn.softmax(logits)
            top_gates, top_experts = jax.lax.top_k(gates, k)
            top_gates = top_gates / (jnp.sum(top_gates, -1, keepdims=True)
                                     + 1e-9)
            expert_ids = top_experts.T.reshape(-1)       # [k*L] choice-major
            weights = top_gates.T.reshape(-1)
            token_ids = jnp.tile(jnp.arange(local_rows), k)
            choice_ids = jnp.arange(k * local_rows) // local_rows
            # global (choice, token) seating key within the expert group
            key = (choice_ids * (expert_ax * local_rows)
                   + me * local_rows + token_ids).astype(jnp.int32)

            # sender-side compaction: choice-major stable seating by expert
            # is (expert, key) order within this sender, so keeping the
            # first send_cap per expert keeps exactly the globally-seatable
            # ones
            position, counts = _seating_positions(expert_ids, experts)
            keep = position < send_cap
            counts_kept = jnp.minimum(counts, send_cap)
            kept_starts = jnp.cumsum(counts_kept) - counts_kept
            send_slot = jnp.where(keep, kept_starts[expert_ids] + position,
                                  send_bound)

            send_rows = jnp.zeros((send_bound, dim), compute)
            send_rows = send_rows.at[send_slot].set(
                rows.astype(compute)[token_ids], mode='drop')
            sentinel_row = jnp.asarray([[experts, key_span]], jnp.int32)
            send_meta = jnp.tile(sentinel_row, (send_bound, 1))
            send_meta = send_meta.at[send_slot].set(
                jnp.stack([expert_ids.astype(jnp.int32), key], axis=1),
                mode='drop')

            # exchange geometry from the gathered count matrix
            dev_counts = counts_kept.reshape(expert_ax, local_experts).sum(
                axis=1).astype(jnp.int32)
            in_off = (jnp.cumsum(dev_counts) - dev_counts).astype(jnp.int32)
            counts_mat = lax.all_gather(dev_counts, EXPERT)  # [sender, dest]
            recv_sz = counts_mat[:, me]
            recv_off = (jnp.cumsum(recv_sz) - recv_sz).astype(jnp.int32)
            out_off = (jnp.cumsum(counts_mat, axis=0) - counts_mat)[me]
            rev_out_off = (jnp.cumsum(counts_mat, axis=1)
                           - counts_mat)[:, me].astype(jnp.int32)
            out_off = out_off.astype(jnp.int32)

            recv_rows = _ragged_exchange(
                transport, EXPERT, send_rows,
                jnp.zeros((recv_bound, dim), compute),
                in_off, dev_counts, out_off, recv_off, recv_sz, rev_out_off)
            recv_meta = _ragged_transport(
                transport, EXPERT, send_meta,
                jnp.tile(sentinel_row, (recv_bound, 1)),
                in_off, dev_counts, out_off, recv_sz)

            # receiver-side seating in global (choice, token) order
            r_expert, r_key = recv_meta[:, 0], recv_meta[:, 1]
            valid = r_expert < experts
            local_e = jnp.clip(r_expert - me * local_experts, 0,
                               local_experts - 1)
            seat_key = jnp.where(valid, local_e * key_span + r_key,
                                 local_experts * key_span)
            order2 = jnp.argsort(seat_key, stable=True)
            ranks2 = jnp.zeros_like(order2).at[order2].set(
                jnp.arange(order2.size))
            e_counts = jnp.bincount(
                jnp.where(valid, local_e, local_experts),
                length=local_experts + 1)[:local_experts]
            e_starts = jnp.cumsum(e_counts) - e_counts
            position2 = ranks2 - e_starts[local_e]
            seat = valid & (position2 < capacity)
            slot2 = jnp.where(seat, local_e * capacity + position2,
                              local_experts * capacity)

            expert_in = jnp.zeros((local_experts * capacity, dim), compute)
            expert_in = expert_in.at[slot2].set(recv_rows, mode='drop')
            expert_in = expert_in.reshape(local_experts, capacity, dim)

            shrunk = self._ffn(expert_in, w1, b1, w2, b2, compute)

            buffer = shrunk.reshape(local_experts * capacity, dim)
            out_rows = buffer.at[slot2].get(mode='fill', fill_value=0)
            returned = _ragged_exchange(
                transport, EXPERT, out_rows,
                jnp.zeros((send_bound, dim), compute),
                recv_off, recv_sz, rev_out_off, in_off, dev_counts, out_off)
            gathered = returned.at[send_slot].get(mode='fill', fill_value=0)
            output = jnp.zeros((local_rows, dim), compute).at[token_ids].add(
                gathered * weights[:, None].astype(compute))

            # Switch balance/z losses over GLOBAL token statistics
            fraction = jnp.mean(jax.nn.one_hot(top_experts[:, 0], experts),
                                axis=0)
            fraction = lax.pmean(fraction, row_axes)
            mean_gates = lax.pmean(jnp.mean(gates, axis=0), row_axes)
            balance = experts * jnp.sum(fraction * mean_gates)
            z_term = lax.pmean(
                jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2), row_axes)
            aux = self.balance_coef * balance + self.z_coef * z_term
            return output, aux

        return run(flat, router, w1, b1, w2, b2)


def moe_partition_rules():
    """Sharding rules for stacked expert weights: experts over the
    ``expert`` axis, FFN hidden over ``model`` (TP within an expert)."""
    return (
        (r'moe/w1$', P(EXPERT, None, 'model')),
        (r'moe/b1$', P(EXPERT, 'model')),
        (r'moe/w2$', P(EXPERT, 'model', None)),
        (r'moe/b2$', P(EXPERT, None)),
        (r'moe/router$', P()),
    )
