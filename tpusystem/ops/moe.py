"""Mixture-of-experts — expert parallelism over the ``expert`` mesh axis.

The reference has only a dense MLP (SURVEY.md §2.4: "EP/MoE | absent");
this module supplies the TPU-native design: experts live as one stacked
weight tensor with a leading ``experts`` dimension sharded over the
``expert`` mesh axis. Token routing has two formulations behind one layer:

* **sparse** (single-shard default): sort/segment dispatch — a stable
  argsort by expert id gives each assignment its position-in-expert, and
  scatter/gather moves only the O(tokens·k) selected rows. This is the
  scalable path: the dense tensors are O(tokens·experts·capacity) ≈
  O(tokens²·k) in both memory and FLOPs.
* **dense** (expert-sharded meshes): one-hot dispatch/combine einsums (the
  Switch-Transformer/GSPMD formulation). With the dispatched activations
  sharding-constrained to the expert axis, XLA inserts the all-to-alls
  over ICI itself — no hand-written collective. Neither the global argsort
  nor the slot scatter partitions along the token axis, so
  ``dispatch='auto'`` keeps the dense form on any multi-device mesh.

Capacity model: each expert processes at most
``capacity = round(k * tokens / experts * capacity_factor)`` tokens per
batch; overflow tokens fall through the residual connection (standard
drop-token semantics). Router runs in float32 with a load-balance loss
(Switch eq. 4) plus a router z-loss for logit stability; the layer returns
``(output, aux_loss)`` and :class:`tpusystem.train.losses.WithAuxLoss`
folds the aux term into any base criterion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from tpusystem.parallel.mesh import EXPERT


def expert_capacity(tokens: int, experts: int, k: int,
                    capacity_factor: float) -> int:
    """Per-expert token budget (at least 1, at most all tokens)."""
    return max(1, min(tokens, int(tokens * k * capacity_factor / experts)))


def route_top_k(gates: jax.Array, k: int, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    Args:
        gates: [tokens, experts] router probabilities (float32).
        k: choices per token; chosen gates renormalize to sum to 1.
        capacity: per-expert slot budget.

    Returns:
        dispatch: [tokens, experts, capacity] 0/1 routing tensor.
        combine: same shape, dispatch weighted by the (renormalized) gate.
        fraction: [experts] fraction of tokens whose *first* choice was the
            expert (the load-balance loss term).

    Slots are granted choice-major: every token's first choice is seated
    before any second choice, and within a choice in token order — so drop
    behavior is deterministic and first choices always win over overflow.
    """
    tokens, experts = gates.shape
    top_gates, top_experts = jax.lax.top_k(gates, k)
    top_gates = top_gates / (jnp.sum(top_gates, -1, keepdims=True) + 1e-9)

    dispatch = jnp.zeros((tokens, experts, capacity), jnp.float32)
    combine = jnp.zeros((tokens, experts, capacity), jnp.float32)
    seated = jnp.zeros((experts,), jnp.float32)
    for choice in range(k):
        onehot = jax.nn.one_hot(top_experts[:, choice], experts)  # [N, E]
        position = jnp.cumsum(onehot, axis=0) - 1 + seated
        seated = seated + jnp.sum(onehot, axis=0)
        fits = (position < capacity) * onehot
        slot = jax.nn.one_hot(position.astype(jnp.int32), capacity)  # [N, E, C]
        placed = fits[:, :, None] * slot
        dispatch = dispatch + placed
        combine = combine + placed * top_gates[:, choice][:, None, None]
    first_choice = jax.nn.one_hot(top_experts[:, 0], experts)
    fraction = jnp.mean(first_choice, axis=0)
    return dispatch, combine, fraction


def route_top_k_sparse(gates: jax.Array, k: int, capacity: int):
    """Sort-based routing: the O(tokens·k) replacement for the dense
    [tokens, experts, capacity] one-hot tensors (SURVEY §2.4 mandates
    ragged-style dispatch; the dense einsums are an O(tokens²)·k FLOP and
    memory cliff at real expert counts).

    Returns ``(token_ids, slots, weights, fraction)`` flat per-assignment
    arrays (length ``tokens*k``): assignment ``i`` sends token
    ``token_ids[i]`` to buffer row ``slots[i]`` (``experts*capacity`` means
    dropped — scatter/gather with ``mode='drop'``/``fill`` discards it) and
    its output is combined back with ``weights[i]``.

    Seating matches :func:`route_top_k` exactly: assignments are flattened
    choice-major and position-in-expert comes from a *stable* sort by
    expert id, so every first choice seats before any second choice and
    within a choice tokens seat in order.
    """
    tokens, experts = gates.shape
    top_gates, top_experts = jax.lax.top_k(gates, k)
    top_gates = top_gates / (jnp.sum(top_gates, -1, keepdims=True) + 1e-9)

    expert_ids = top_experts.T.reshape(-1)             # [k*N] choice-major
    weights = top_gates.T.reshape(-1)
    token_ids = jnp.tile(jnp.arange(tokens), k)

    order = jnp.argsort(expert_ids, stable=True)
    # invert the permutation with one scatter (a second argsort is O(n log n))
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(order.size))
    counts = jnp.bincount(expert_ids, length=experts)
    starts = jnp.cumsum(counts) - counts
    position = ranks - starts[expert_ids]              # position within expert
    keep = position < capacity
    slots = jnp.where(keep, expert_ids * capacity + position,
                      experts * capacity)              # out of range = dropped

    fraction = jnp.mean(jax.nn.one_hot(top_experts[:, 0], experts), axis=0)
    return token_ids, slots, weights, fraction


class MoEMLP(nn.Module):
    """Expert-parallel FFN: drop-in for the dense fc->gelu->proj block.

    Returns ``(output, aux_loss)`` where ``aux_loss`` already carries the
    configured coefficients. Weights are stacked [experts, ...] float32
    masters cast to ``dtype`` per use; pass ``mesh`` to pin the dispatched
    activations to the expert axis (otherwise GSPMD chooses).
    """

    experts: int
    k: int = 2
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    balance_coef: float = 1e-2
    z_coef: float = 1e-3
    mesh: object = None
    dispatch: str = 'auto'   # 'sparse' | 'dense' | 'auto'

    @nn.compact
    def __call__(self, hidden):
        batch_shape, dim = hidden.shape[:-1], hidden.shape[-1]
        hidden_dim = self.mlp_ratio * dim
        flat = hidden.reshape(-1, dim)
        tokens = flat.shape[0]

        router = self.param('router', nn.initializers.normal(0.02),
                            (dim, self.experts), jnp.float32)
        init = nn.initializers.lecun_normal()
        w1 = self.param('w1', init, (self.experts, dim, hidden_dim), jnp.float32)
        b1 = self.param('b1', nn.initializers.zeros, (self.experts, hidden_dim), jnp.float32)
        w2 = self.param('w2', init, (self.experts, hidden_dim, dim), jnp.float32)
        b2 = self.param('b2', nn.initializers.zeros, (self.experts, dim), jnp.float32)

        logits = flat.astype(jnp.float32) @ router
        gates = jax.nn.softmax(logits)
        capacity = expert_capacity(tokens, self.experts, self.k,
                                   self.capacity_factor)

        # 'sparse' is the O(tokens·k) sort/scatter path — the single-shard
        # default. Neither the global argsort nor the slot scatter is
        # partitionable along the token axis, so under ANY multi-device
        # mesh (expert-, data- or tensor-sharded) 'auto' keeps the dense
        # one-hot einsums, which GSPMD partitions freely (and whose EP
        # all-to-all it inserts itself).
        mode = self.dispatch
        if mode == 'auto':
            multi_device = self.mesh is not None and self.mesh.size > 1
            mode = 'dense' if multi_device else 'sparse'
        if mode not in ('sparse', 'dense'):
            raise ValueError(f'unknown dispatch {self.dispatch!r}; '
                             "expected 'sparse', 'dense' or 'auto'")
        compute = jnp.dtype(self.dtype)

        if mode == 'sparse':
            token_ids, slots, weights, fraction = route_top_k_sparse(
                gates, self.k, capacity)
            rows = flat.astype(compute)[token_ids]     # [k*N, D] gather
            expert_in = jnp.zeros((self.experts * capacity, dim), compute)
            expert_in = expert_in.at[slots].set(rows, mode='drop')
            expert_in = expert_in.reshape(self.experts, capacity, dim)
        else:
            dispatch, combine, fraction = route_top_k(gates, self.k, capacity)
            expert_in = jnp.einsum('nec,nd->ecd', dispatch.astype(compute),
                                   flat.astype(compute))

        # Switch load-balance loss: experts * <fraction_dispatched * mean_prob>
        balance = self.experts * jnp.sum(fraction * jnp.mean(gates, axis=0))
        z_term = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        aux = self.balance_coef * balance + self.z_coef * z_term

        expert_in = self._constrain(expert_in)
        grown = jnp.einsum('ecd,edh->ech', expert_in, w1.astype(compute))
        grown = nn.gelu(grown + b1[:, None].astype(compute))
        shrunk = jnp.einsum('ech,ehd->ecd', grown, w2.astype(compute))
        shrunk = shrunk + b2[:, None].astype(compute)
        shrunk = self._constrain(shrunk)

        if mode == 'sparse':
            buffer = shrunk.reshape(self.experts * capacity, dim)
            gathered = buffer.at[slots].get(mode='fill', fill_value=0)
            output = jnp.zeros((tokens, dim), compute).at[token_ids].add(
                gathered * weights[:, None].astype(compute))
        else:
            output = jnp.einsum('nec,ecd->nd', combine.astype(compute), shrunk)
        return output.reshape(*batch_shape, dim).astype(hidden.dtype), aux

    def _constrain(self, value):
        if self.mesh is None or self.mesh.shape[EXPERT] == 1:
            return value
        sharding = NamedSharding(self.mesh, P(EXPERT, None, None))
        return jax.lax.with_sharding_constraint(value, sharding)


def moe_partition_rules():
    """Sharding rules for stacked expert weights: experts over the
    ``expert`` axis, FFN hidden over ``model`` (TP within an expert)."""
    return (
        (r'moe/w1$', P(EXPERT, None, 'model')),
        (r'moe/b1$', P(EXPERT, 'model')),
        (r'moe/w2$', P(EXPERT, 'model', None)),
        (r'moe/b2$', P(EXPERT, None)),
        (r'moe/router$', P()),
    )
