"""Mixture-of-experts — expert parallelism over the ``expert`` mesh axis.

The reference has only a dense MLP (SURVEY.md §2.4: "EP/MoE | absent");
this module supplies the TPU-native design: experts live as one stacked
weight tensor with a leading ``experts`` dimension sharded over the
``expert`` mesh axis. Token routing has two formulations behind one layer:

* **sparse**: sort/segment dispatch — a stable argsort by expert id
  gives each assignment its position-in-expert, and scatter/gather moves
  only the O(tokens·k) selected rows (the dense tensors are
  O(tokens·experts·capacity) ≈ O(tokens²·k) in memory and FLOPs).
  Single-shard it runs directly; on multi-device meshes it runs inside
  ``shard_map`` with token rows sharded over (data, fsdp, seq, expert)
  and a regular differentiable ``all_to_all`` carrying each sender's
  fixed per-expert quota to the expert's owner — SURVEY §2.4's
  ragged-style exchange, made static-shaped by quota padding.
* **dense**: one-hot dispatch/combine einsums (the Switch/GSPMD
  formulation); the partitioner shards them freely and inserts the
  collectives itself. ``dispatch='auto'`` falls back here when the
  sharded-sparse preconditions fail (indivisible rows/experts, model-axis
  TP inside experts).

Capacity model: each expert processes at most
``capacity = round(k * tokens / experts * capacity_factor)`` tokens per
batch; overflow tokens fall through the residual connection (standard
drop-token semantics). Router runs in float32 with a load-balance loss
(Switch eq. 4) plus a router z-loss for logit stability; the layer returns
``(output, aux_loss)`` and :class:`tpusystem.train.losses.WithAuxLoss`
folds the aux term into any base criterion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from tpusystem.parallel.mesh import EXPERT


def expert_capacity(tokens: int, experts: int, k: int,
                    capacity_factor: float) -> int:
    """Per-expert token budget (at least 1, at most all tokens)."""
    return max(1, min(tokens, int(tokens * k * capacity_factor / experts)))


def route_top_k(gates: jax.Array, k: int, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    Args:
        gates: [tokens, experts] router probabilities (float32).
        k: choices per token; chosen gates renormalize to sum to 1.
        capacity: per-expert slot budget.

    Returns:
        dispatch: [tokens, experts, capacity] 0/1 routing tensor.
        combine: same shape, dispatch weighted by the (renormalized) gate.
        fraction: [experts] fraction of tokens whose *first* choice was the
            expert (the load-balance loss term).

    Slots are granted choice-major: every token's first choice is seated
    before any second choice, and within a choice in token order — so drop
    behavior is deterministic and first choices always win over overflow.
    """
    tokens, experts = gates.shape
    top_gates, top_experts = jax.lax.top_k(gates, k)
    top_gates = top_gates / (jnp.sum(top_gates, -1, keepdims=True) + 1e-9)

    dispatch = jnp.zeros((tokens, experts, capacity), jnp.float32)
    combine = jnp.zeros((tokens, experts, capacity), jnp.float32)
    seated = jnp.zeros((experts,), jnp.float32)
    for choice in range(k):
        onehot = jax.nn.one_hot(top_experts[:, choice], experts)  # [N, E]
        position = jnp.cumsum(onehot, axis=0) - 1 + seated
        seated = seated + jnp.sum(onehot, axis=0)
        fits = (position < capacity) * onehot
        slot = jax.nn.one_hot(position.astype(jnp.int32), capacity)  # [N, E, C]
        placed = fits[:, :, None] * slot
        dispatch = dispatch + placed
        combine = combine + placed * top_gates[:, choice][:, None, None]
    first_choice = jax.nn.one_hot(top_experts[:, 0], experts)
    fraction = jnp.mean(first_choice, axis=0)
    return dispatch, combine, fraction


def route_top_k_sparse(gates: jax.Array, k: int, capacity: int):
    """Sort-based routing: the O(tokens·k) replacement for the dense
    [tokens, experts, capacity] one-hot tensors (SURVEY §2.4 mandates
    ragged-style dispatch; the dense einsums are an O(tokens²)·k FLOP and
    memory cliff at real expert counts).

    Returns ``(token_ids, slots, weights, fraction)`` flat per-assignment
    arrays (length ``tokens*k``): assignment ``i`` sends token
    ``token_ids[i]`` to buffer row ``slots[i]`` (``experts*capacity`` means
    dropped — scatter/gather with ``mode='drop'``/``fill`` discards it) and
    its output is combined back with ``weights[i]``.

    Seating matches :func:`route_top_k` exactly: assignments are flattened
    choice-major and position-in-expert comes from a *stable* sort by
    expert id, so every first choice seats before any second choice and
    within a choice tokens seat in order.
    """
    tokens, experts = gates.shape
    top_gates, top_experts = jax.lax.top_k(gates, k)
    top_gates = top_gates / (jnp.sum(top_gates, -1, keepdims=True) + 1e-9)

    expert_ids = top_experts.T.reshape(-1)             # [k*N] choice-major
    weights = top_gates.T.reshape(-1)
    token_ids = jnp.tile(jnp.arange(tokens), k)

    order = jnp.argsort(expert_ids, stable=True)
    # invert the permutation with one scatter (a second argsort is O(n log n))
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(order.size))
    counts = jnp.bincount(expert_ids, length=experts)
    starts = jnp.cumsum(counts) - counts
    position = ranks - starts[expert_ids]              # position within expert
    keep = position < capacity
    slots = jnp.where(keep, expert_ids * capacity + position,
                      experts * capacity)              # out of range = dropped

    fraction = jnp.mean(jax.nn.one_hot(top_experts[:, 0], experts), axis=0)
    return token_ids, slots, weights, fraction


class MoEMLP(nn.Module):
    """Expert-parallel FFN: drop-in for the dense fc->gelu->proj block.

    Returns ``(output, aux_loss)`` where ``aux_loss`` already carries the
    configured coefficients. Weights are stacked [experts, ...] float32
    masters cast to ``dtype`` per use; pass ``mesh`` to pin the dispatched
    activations to the expert axis (otherwise GSPMD chooses).
    """

    experts: int
    k: int = 2
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    balance_coef: float = 1e-2
    z_coef: float = 1e-3
    mesh: object = None
    dispatch: str = 'auto'   # 'sparse' | 'dense' | 'auto'

    @nn.compact
    def __call__(self, hidden):
        batch_shape, dim = hidden.shape[:-1], hidden.shape[-1]
        hidden_dim = self.mlp_ratio * dim
        flat = hidden.reshape(-1, dim)
        tokens = flat.shape[0]

        router = self.param('router', nn.initializers.normal(0.02),
                            (dim, self.experts), jnp.float32)
        init = nn.initializers.lecun_normal()
        w1 = self.param('w1', init, (self.experts, dim, hidden_dim), jnp.float32)
        b1 = self.param('b1', nn.initializers.zeros, (self.experts, hidden_dim), jnp.float32)
        w2 = self.param('w2', init, (self.experts, hidden_dim, dim), jnp.float32)
        b2 = self.param('b2', nn.initializers.zeros, (self.experts, dim), jnp.float32)

        # 'sparse' is the O(tokens·k) sort/scatter path. Single-shard it
        # runs directly; on a multi-device mesh it runs inside shard_map
        # with token rows sharded over (data, fsdp, expert) and a regular
        # all_to_all moving each sender's per-expert quota to the expert's
        # owner (_sharded_sparse — SURVEY §2.4's ragged-style dispatch,
        # made exchangeable with static shapes by fixed per-sender
        # quotas). 'auto' falls back to the dense one-hot einsums when the
        # sharded preconditions don't hold (divisibility, unsharded model
        # axis); explicit 'sparse' raises instead of silently degrading.
        mode = self.dispatch
        if mode == 'auto':
            if self.mesh is None or self.mesh.size == 1:
                mode = 'sparse'
            else:
                problem = self._sharded_sparse_blocker(tokens)
                mode = 'dense' if problem else 'sparse_sharded'
        elif mode == 'sparse':
            if self.mesh is not None and self.mesh.size > 1:
                problem = self._sharded_sparse_blocker(tokens)
                if problem:
                    raise ValueError(
                        f'dispatch=sparse on a multi-device mesh: {problem} '
                        f"(use dispatch='auto' to fall back to dense)")
                mode = 'sparse_sharded'
        elif mode != 'dense':
            raise ValueError(f'unknown dispatch {self.dispatch!r}; '
                             "expected 'sparse', 'dense' or 'auto'")
        compute = jnp.dtype(self.dtype)

        if mode == 'sparse_sharded':
            output, aux = self._sharded_sparse(flat, router, w1, b1, w2, b2,
                                               compute)
            return output.reshape(*batch_shape, dim).astype(hidden.dtype), aux

        logits = flat.astype(jnp.float32) @ router
        gates = jax.nn.softmax(logits)
        capacity = expert_capacity(tokens, self.experts, self.k,
                                   self.capacity_factor)

        if mode == 'sparse':
            token_ids, slots, weights, fraction = route_top_k_sparse(
                gates, self.k, capacity)
            rows = flat.astype(compute)[token_ids]     # [k*N, D] gather
            expert_in = jnp.zeros((self.experts * capacity, dim), compute)
            expert_in = expert_in.at[slots].set(rows, mode='drop')
            expert_in = expert_in.reshape(self.experts, capacity, dim)
        else:
            dispatch, combine, fraction = route_top_k(gates, self.k, capacity)
            expert_in = jnp.einsum('nec,nd->ecd', dispatch.astype(compute),
                                   flat.astype(compute))

        # Switch load-balance loss: experts * <fraction_dispatched * mean_prob>
        balance = self.experts * jnp.sum(fraction * jnp.mean(gates, axis=0))
        z_term = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        aux = self.balance_coef * balance + self.z_coef * z_term

        expert_in = self._constrain(expert_in)
        shrunk = self._ffn(expert_in, w1, b1, w2, b2, compute)
        shrunk = self._constrain(shrunk)

        if mode == 'sparse':
            buffer = shrunk.reshape(self.experts * capacity, dim)
            output = self._sparse_combine(buffer, slots, token_ids, weights,
                                          tokens, dim, compute)
        else:
            output = jnp.einsum('nec,ecd->nd', combine.astype(compute), shrunk)
        return output.reshape(*batch_shape, dim).astype(hidden.dtype), aux

    def _ffn(self, expert_in, w1, b1, w2, b2, compute):
        """The per-expert MLP — one implementation for every dispatch path,
        so the parity the tests pin cannot drift."""
        grown = jnp.einsum('ecd,edh->ech', expert_in, w1.astype(compute))
        grown = nn.gelu(grown + b1[:, None].astype(compute))
        return (jnp.einsum('ech,ehd->ecd', grown, w2.astype(compute))
                + b2[:, None].astype(compute))

    @staticmethod
    def _sparse_combine(buffer, slots, token_ids, weights, tokens, dim,
                        compute):
        gathered = buffer.at[slots].get(mode='fill', fill_value=0)
        return jnp.zeros((tokens, dim), compute).at[token_ids].add(
            gathered * weights[:, None].astype(compute))

    def _constrain(self, value):
        if self.mesh is None or self.mesh.shape[EXPERT] == 1:
            return value
        sharding = NamedSharding(self.mesh, P(EXPERT, None, None))
        return jax.lax.with_sharding_constraint(value, sharding)

    def _sharded_sparse_blocker(self, tokens: int) -> str | None:
        """Why the sharded sparse path cannot run (None = it can)."""
        from tpusystem.parallel.mesh import DATA, FSDP, MODEL, SEQ
        shape = dict(self.mesh.shape)
        shards = (shape.get(DATA, 1) * shape.get(FSDP, 1)
                  * shape.get(SEQ, 1) * shape.get(EXPERT, 1))
        if shape.get(MODEL, 1) > 1:
            return 'model-axis TP inside experts is dense-only'
        if self.experts % shape.get(EXPERT, 1):
            return (f'{self.experts} experts not divisible by the expert '
                    f'axis ({shape.get(EXPERT, 1)})')
        if tokens % shards:
            return (f'{tokens} token rows not divisible by '
                    f'data*fsdp*seq*expert = {shards}')
        return None

    def _sharded_sparse(self, flat, router, w1, b1, w2, b2, compute):
        """Expert-parallel sparse dispatch inside ``shard_map``.

        Token rows shard over (data, fsdp, expert); each device seats its
        assignments into a ``[experts, quota]`` send buffer with
        :func:`route_top_k_sparse` (quota = its share of the global
        capacity), one **regular** ``all_to_all`` over the expert axis
        hands every expert's rows to its owner, the FFN runs on
        ``[local_experts, senders*quota]`` seated rows (no receiver-side
        sort), and the inverse exchange brings outputs home for the
        weighted combine. Fixed per-sender quotas are what make the
        exchange static-shaped — the ragged-a2a formulation SURVEY §2.4
        calls for, with padding instead of raggedness; ``all_to_all``
        differentiates (its transpose is the reverse exchange), so the
        whole path trains. Capacity semantics differ from the dense path:
        drops are decided per sender (choice-major within each shard), not
        by global token order — with ample capacity (no drops) the two
        paths agree exactly.
        """
        import functools

        from jax import lax

        from tpusystem.parallel.mesh import DATA, FSDP, SEQ

        mesh = self.mesh
        expert_ax = mesh.shape[EXPERT]
        local_experts = self.experts // expert_ax
        shards = (mesh.shape[DATA] * mesh.shape[FSDP] * mesh.shape[SEQ]
                  * expert_ax)
        local_rows = flat.shape[0] // shards
        # clamp like expert_capacity: a sender cannot route more than its
        # local_rows assignments to any one expert, so a larger quota only
        # pads the all_to_all buffers with unreachable zero rows
        quota = max(1, min(local_rows,
                           int(local_rows * self.k * self.capacity_factor
                               / self.experts)))
        dim = flat.shape[1]
        experts, k = self.experts, self.k
        row_axes = (DATA, FSDP, SEQ, EXPERT)
        row_spec = P(row_axes, None)

        @functools.partial(
            jax.shard_map, mesh=mesh, check_vma=False,
            in_specs=(row_spec, P(), P(EXPERT, None, None), P(EXPERT, None),
                      P(EXPERT, None, None), P(EXPERT, None)),
            out_specs=(row_spec, P()))
        def run(rows, router, w1, b1, w2, b2):
            logits = rows.astype(jnp.float32) @ router
            gates = jax.nn.softmax(logits)
            token_ids, slots, weights, fraction = route_top_k_sparse(
                gates, k, quota)

            send = jnp.zeros((experts * quota, dim), compute)
            send = send.at[slots].set(rows.astype(compute)[token_ids],
                                      mode='drop')
            # chunk d of the send buffer (global expert order, owners
            # contiguous) goes to device d; twice the same tiled exchange
            # is the identity, which is how outputs come home below
            recv = lax.all_to_all(send, EXPERT, split_axis=0, concat_axis=0,
                                  tiled=True)
            expert_in = (recv.reshape(expert_ax, local_experts, quota, dim)
                         .transpose(1, 0, 2, 3)
                         .reshape(local_experts, expert_ax * quota, dim))

            shrunk = self._ffn(expert_in, w1, b1, w2, b2, compute)

            back = (shrunk.reshape(local_experts, expert_ax, quota, dim)
                    .transpose(1, 0, 2, 3)
                    .reshape(experts * quota, dim))
            buffer = lax.all_to_all(back, EXPERT, split_axis=0, concat_axis=0,
                                    tiled=True)
            output = self._sparse_combine(buffer, slots, token_ids,
                                          weights, rows.shape[0], dim,
                                          compute)

            # Switch balance/z losses over GLOBAL token statistics
            fraction = lax.pmean(fraction, row_axes)
            mean_gates = lax.pmean(jnp.mean(gates, axis=0), row_axes)
            balance = experts * jnp.sum(fraction * mean_gates)
            z_term = lax.pmean(
                jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2), row_axes)
            aux = self.balance_coef * balance + self.z_coef * z_term
            return output, aux

        return run(flat, router, w1, b1, w2, b2)


def moe_partition_rules():
    """Sharding rules for stacked expert weights: experts over the
    ``expert`` axis, FFN hidden over ``model`` (TP within an expert)."""
    return (
        (r'moe/w1$', P(EXPERT, None, 'model')),
        (r'moe/b1$', P(EXPERT, 'model')),
        (r'moe/w2$', P(EXPERT, 'model', None)),
        (r'moe/b2$', P(EXPERT, None)),
        (r'moe/router$', P()),
    )
