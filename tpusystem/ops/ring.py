"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability (SURVEY.md §2.4/§5): queries stay put while K/V
chunks rotate around the ICI ring via ``ppermute``; each device accumulates
blockwise-softmax partial results, so a sequence of length S costs each
device O(S/n) memory and the full S^2 attention FLOPs are spread n ways.

Two variants:

* :func:`ring_attention` — the ppermute ring, callable **inside**
  ``shard_map`` on seq-sharded [B, S/n, H, D] chunks. Each rotating KV
  chunk is attended with the Pallas **flash kernel** and partials merge by
  logsumexp weights, so per-device memory stays O(S/n) even inside the
  chunk. Differentiable end to end (``ppermute`` has a transpose rule; the
  kernel's custom_vjp accepts the lse cotangent the merge produces).
* :func:`ulysses_attention` — the all-to-all head/sequence swap (DeepSpeed
  Ulysses): transposes shards so each device holds *all* positions for a
  subset of heads, runs flash attention locally, swaps back. Cheaper
  collectives for moderate contexts; requires heads % ring_size == 0.

The outer convenience :func:`ring_self_attention` wires the ``shard_map``
over a mesh for both.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.ops.attention import NEG_INF
from tpusystem.parallel.mesh import DATA, FSDP, SEQ


def _attention_lse(query, key, value, *, causal, scale, inner):
    """One chunk's ``(out, lse)`` pair via the chosen inner kernel.

    ``'flash'`` is the Pallas O(chunk)-memory kernel (the capability that
    makes long context viable — VERDICT r1 #4); ``'einsum'`` is the XLA
    reference fallback. Both return lse as [B, S, H] float32.
    """
    from tpusystem.ops.pallas.flash import (_xla_attention_lse,
                                            flash_attention_lse)
    if inner == 'flash':
        return flash_attention_lse(query, key, value, causal=causal,
                                   scale=scale)
    if inner == 'einsum':
        return _xla_attention_lse(query, key, value, causal=causal,
                                  scale=scale)
    raise ValueError(f"unknown inner kernel {inner!r}; "
                     "expected 'flash' or 'einsum'")


def ring_attention(query, key, value, *, axis: str = SEQ, causal: bool = True,
                   scale: float | None = None, inner: str = 'flash'):
    """Blockwise ring attention. Call inside ``shard_map``.

    K/V chunks rotate around the ring; each arriving chunk is attended with
    the **flash kernel** and the per-chunk ``(out, lse)`` partials merge by
    logsumexp weighting — exact blockwise softmax, O(chunk) memory. Causal
    masking needs no in-kernel offsets: step 0 attends the device's own
    chunk causally, and every later step's chunk is either strictly past
    (fully visible, non-causal flash) or strictly future (discarded by
    setting its merge weight to exp(-inf)).

    Args:
        query/key/value: local chunks [batch, chunk, heads, head_dim] of a
            sequence sharded over ``axis``.
        inner: ``'flash'`` (Pallas kernel per chunk) or ``'einsum'``
            (XLA reference fallback).
    Returns:
        local output chunk [batch, chunk, heads, head_dim].
    """
    ring = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    head_dim = query.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5

    def permute(tensor):
        return lax.ppermute(
            tensor, axis,
            [(source, (source + 1) % ring) for source in range(ring)])

    # step 0: own chunk (the causal diagonal block)
    out, lse = _attention_lse(query, key, value, causal=causal, scale=scale,
                              inner=inner)
    out = out.astype(jnp.float32)

    for step in range(1, ring):
        key, value = permute(key), permute(value)
        # we now hold the chunk of rank (rank - step) % ring: strictly past
        # iff rank >= step, strictly future otherwise (causal only)
        chunk_out, chunk_lse = _attention_lse(query, key, value, causal=False,
                                              scale=scale, inner=inner)
        if causal:
            visible = rank >= step
            chunk_lse = jnp.where(visible, chunk_lse, NEG_INF)
            chunk_out = jnp.where(visible, chunk_out, 0)
        merged = jnp.logaddexp(lse, chunk_lse)
        weight_old = jnp.exp(lse - merged)[..., None]
        weight_new = jnp.exp(chunk_lse - merged)[..., None]
        out = out * weight_old + chunk_out.astype(jnp.float32) * weight_new
        lse = merged

    return out.astype(query.dtype)


def ulysses_attention(query, key, value, *, axis: str = SEQ,
                      causal: bool = True, scale: float | None = None):
    """All-to-all sequence parallelism. Call inside ``shard_map``.

    Local [B, S/n, H, D] chunks are shard-transposed to [B, S, H/n, D]
    (full sequence, head subset), attended with the flash kernel, and
    transposed back.
    """
    ring = lax.axis_size(axis)
    heads = query.shape[2]
    assert heads % ring == 0, (
        f'ulysses needs heads ({heads}) divisible by the seq axis ({ring})')

    def swap_in(tensor):   # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(tensor, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def swap_out(tensor):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(tensor, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    from tpusystem.ops.pallas.flash import flash_attention
    out = flash_attention(swap_in(query), swap_in(key), swap_in(value),
                          causal=causal, scale=scale)
    return swap_out(out)


def ring_self_attention(query, key, value, mesh, *, causal: bool = True,
                        variant: str = 'ring', inner: str = 'flash'):
    """Convenience wrapper: shard_map the chosen variant over ``mesh``.

    Inputs are global [B, S, H, D]; batch shards over (data, fsdp), sequence
    over seq. ``inner`` selects ring's per-chunk kernel ('flash'|'einsum').
    Useful standalone and as the reference harness for tests.
    """
    if variant == 'ring':
        implementation = functools.partial(ring_attention, inner=inner)
    elif variant == 'ulysses':
        implementation = ulysses_attention
    else:
        raise ValueError(f'unknown variant {variant!r}; '
                         "expected 'ring' or 'ulysses'")
    data_parallel = mesh.shape[DATA] * mesh.shape[FSDP]
    # batch shards over (data, fsdp) when divisible (e.g. module.init traces
    # with batch 1 — replicate batch there, shard only the sequence)
    batch_axes = (DATA, FSDP) if query.shape[0] % data_parallel == 0 else None
    spec = P(batch_axes, SEQ, None, None)

    # check_vma=False: the flash pallas_call inside carries no
    # varying-mesh-axis info for the replication checker
    @functools.partial(
        jax.shard_map, mesh=mesh, check_vma=False,
        in_specs=(spec, spec, spec), out_specs=spec)
    def mapped(q, k, v):
        return implementation(q, k, v, causal=causal)

    return mapped(query, key, value)
