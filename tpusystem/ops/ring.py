"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability (SURVEY.md §2.4/§5): queries stay put while K/V
chunks rotate around the ICI ring via ``ppermute``; each device accumulates
blockwise-softmax partial results, so a sequence of length S costs each
device O(S/n) memory and the full S^2 attention FLOPs are spread n ways.

Three variants:

* :func:`zigzag_ring_attention` — the **causal** ring. Each device holds a
  zigzag stripe pair (stripe ``i`` and stripe ``2n-1-i`` of ``2n``), which
  balances causal work perfectly: every device computes exactly the visible
  half of each arriving KV pair instead of computing the full block and
  masking half of it away (the contiguous-layout ring wastes ~2x FLOPs on
  discarded future chunks, and rank 0 idles while rank n-1 sweats). The
  next step's ``ppermute`` is issued *before* the current step's flash
  calls so XLA's latency-hiding scheduler can overlap transfer with
  compute (SURVEY.md §7.3: "overlap ppermute with compute").
* :func:`ring_attention` — the contiguous-layout ring, kept for the
  non-causal case (where every chunk is visible and there is nothing to
  skip) and for sequence lengths the zigzag split cannot tile.
* :func:`ulysses_attention` — the all-to-all head/sequence swap (DeepSpeed
  Ulysses): transposes shards so each device holds *all* positions for a
  subset of heads, runs flash attention locally, swaps back. Cheaper
  collectives for moderate contexts; requires heads % ring_size == 0.

Each rotating KV chunk is attended with the Pallas **flash kernel** and
partials merge by logsumexp weights, so per-device memory stays O(S/n)
even inside the chunk. Differentiable end to end (``ppermute`` has a
transpose rule; the kernel's custom_vjp accepts the lse cotangent the
merge produces).

The outer convenience :func:`ring_self_attention` wires the ``shard_map``
over a mesh for all variants; causal ``'ring'`` auto-upgrades to zigzag
whenever the sequence length allows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.ops.attention import NEG_INF
from tpusystem.parallel.mesh import DATA, FSDP, SEQ, axis_size, shard_map


def _attention_lse(query, key, value, *, causal, scale, inner):
    """One chunk's ``(out, lse)`` pair via the chosen inner kernel.

    ``'flash'`` is the Pallas O(chunk)-memory kernel (the capability that
    makes long context viable — VERDICT r1 #4); ``'einsum'`` is the XLA
    reference fallback. Both return lse as [B, S, H] float32. Grouped
    (GQA) K/V is accepted at its own head count: flash shares KV across
    each query-head group in-kernel, the einsum fallback broadcasts.
    """
    from tpusystem.ops.attention import repeat_kv_heads
    from tpusystem.ops.pallas.flash import (_xla_attention_lse,
                                            flash_attention_lse)
    if inner == 'flash':
        return flash_attention_lse(query, key, value, causal=causal,
                                   scale=scale)
    if inner == 'einsum':
        key, value = repeat_kv_heads(query, key, value)
        return _xla_attention_lse(query, key, value, causal=causal,
                                  scale=scale)
    raise ValueError(f"unknown inner kernel {inner!r}; "
                     "expected 'flash' or 'einsum'")


def _merge_lse(out, lse, new_out, new_lse):
    """Fold a new ``(out, lse)`` partial into the f32 accumulator pair.

    Exact blockwise softmax: both partials are weighted by
    ``exp(lse - logaddexp(lse, new_lse))``. A partial carrying
    ``lse = NEG_INF`` contributes exactly zero, so masked-out blocks fold
    to a no-op.
    """
    merged = jnp.logaddexp(lse, new_lse)
    weight_old = jnp.exp(lse - merged)[..., None]
    weight_new = jnp.exp(new_lse - merged)[..., None]
    return out * weight_old + new_out.astype(jnp.float32) * weight_new, merged


def _ring_permute(axis: str, ring: int):
    def permute(tensor):
        return lax.ppermute(
            tensor, axis,
            [(source, (source + 1) % ring) for source in range(ring)])
    return permute


def ring_attention(query, key, value, *, axis: str = SEQ, causal: bool = True,
                   scale: float | None = None, inner: str = 'flash'):
    """Blockwise ring attention, contiguous layout. Call inside ``shard_map``.

    K/V chunks rotate around the ring; each arriving chunk is attended with
    the **flash kernel** and the per-chunk ``(out, lse)`` partials merge by
    logsumexp weighting — exact blockwise softmax, O(chunk) memory. Causal
    masking needs no in-kernel offsets: step 0 attends the device's own
    chunk causally, and every later step's chunk is either strictly past
    (fully visible, non-causal flash) or strictly future (discarded by
    setting its merge weight to exp(-inf)).

    Note the causal case pays for every discarded future chunk and leaves
    early ranks idle-equivalent — :func:`zigzag_ring_attention` is the
    balanced formulation and is what :func:`ring_self_attention` selects
    for causal use; this contiguous form remains the non-causal path.

    Args:
        query/key/value: local chunks [batch, chunk, heads, head_dim] of a
            sequence sharded over ``axis``.
        inner: ``'flash'`` (Pallas kernel per chunk) or ``'einsum'``
            (XLA reference fallback).
    Returns:
        local output chunk [batch, chunk, heads, head_dim].
    """
    ring = axis_size(axis)
    rank = lax.axis_index(axis)
    head_dim = query.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    permute = _ring_permute(axis, ring)

    # step 0: own chunk (the causal diagonal block)
    out, lse = _attention_lse(query, key, value, causal=causal, scale=scale,
                              inner=inner)
    out = out.astype(jnp.float32)

    # the chunk for step s+1 is always already in flight before step s's
    # attention runs, so the transfer can hide under the flash call
    if ring > 1:
        key_next, value_next = permute(key), permute(value)
    for step in range(1, ring):
        key, value = key_next, value_next
        if step + 1 < ring:
            key_next, value_next = permute(key), permute(value)
        # we now hold the chunk of rank (rank - step) % ring: strictly past
        # iff rank >= step, strictly future otherwise (causal only)
        chunk_out, chunk_lse = _attention_lse(query, key, value, causal=False,
                                              scale=scale, inner=inner)
        if causal:
            visible = rank >= step
            chunk_lse = jnp.where(visible, chunk_lse, NEG_INF)
            chunk_out = jnp.where(visible, chunk_out, 0)
        out, lse = _merge_lse(out, lse, chunk_out, chunk_lse)

    return out.astype(query.dtype)


def _even_home(stripe: int, ring: int) -> int:
    """Zigzag owner of global stripe ``stripe`` (of ``2 * ring``)."""
    return stripe if stripe < ring else 2 * ring - 1 - stripe


def _to_zigzag(tensor, axis: str, ring: int):
    """Contiguous local chunk -> (low, high) zigzag stripe pair.

    Contiguous layout: device ``i`` holds global stripes ``(2i, 2i+1)`` as
    the two halves of its chunk. Zigzag layout: device ``i`` holds stripes
    ``(i, 2n-1-i)``. The exchange is two half-chunk ``ppermute``s: one
    routing every even-indexed stripe to its zigzag home, one routing the
    odd stripes — each is a valid device permutation because every device
    owns exactly one even and one odd stripe in both layouts. The receiver
    sorts its two arrivals into (low, high) by its own rank parity
    (stripe ``i`` and stripe ``2n-1-i`` always have opposite parity).
    """
    rank = lax.axis_index(axis)
    half = tensor.shape[1] // 2
    first, second = tensor[:, :half], tensor[:, half:]  # stripes 2i, 2i+1
    recv_even = lax.ppermute(
        first, axis, [(i, _even_home(2 * i, ring)) for i in range(ring)])
    recv_odd = lax.ppermute(
        second, axis, [(i, _even_home(2 * i + 1, ring)) for i in range(ring)])
    even_rank = (rank % 2) == 0
    low = jnp.where(even_rank, recv_even, recv_odd)    # stripe rank
    high = jnp.where(even_rank, recv_odd, recv_even)   # stripe 2n-1-rank
    return low, high


def _from_zigzag(low, high, axis: str, ring: int):
    """Inverse of :func:`_to_zigzag`: stripe pair -> contiguous chunk."""
    rank = lax.axis_index(axis)
    even_rank = (rank % 2) == 0
    # device a holds stripes (a, 2n-1-a); its even stripe is `a` when a is
    # even (the low slot), else `2n-1-a` (the high slot)
    payload_even = jnp.where(even_rank, low, high)
    payload_odd = jnp.where(even_rank, high, low)
    even_stripe = lambda a: a if a % 2 == 0 else 2 * ring - 1 - a
    odd_stripe = lambda a: a if a % 2 == 1 else 2 * ring - 1 - a
    first = lax.ppermute(
        payload_even, axis,
        [(a, even_stripe(a) // 2) for a in range(ring)])   # stripe 2i
    second = lax.ppermute(
        payload_odd, axis,
        [(a, odd_stripe(a) // 2) for a in range(ring)])    # stripe 2i+1
    return jnp.concatenate([first, second], axis=1)


def zigzag_ring_attention(query, key, value, *, axis: str = SEQ,
                          scale: float | None = None, inner: str = 'flash'):
    """Causal ring attention with balanced zigzag stripes. Call inside
    ``shard_map``.

    The contiguous-layout causal ring computes every arriving KV chunk in
    full and discards the strictly-future ones — on an n-way ring that is
    ~2x the necessary FLOPs, concentrated on the high ranks while rank 0
    idles. Here the global sequence is viewed as ``2n`` stripes and device
    ``i`` holds the pair ``(i, 2n-1-i)``, so every device's visible work is
    identical at every step:

    * step 0 (own pair): ``q_low @ kv_low`` causal, ``q_high @ kv_low``
      full, ``q_high @ kv_high`` causal — the diagonal.
    * step s, KV pair arriving from rank ``j = (rank - s) mod n``:
      ``q_high @ kv_low`` is *always* fully visible (stripe ``j < n`` is
      always in the past of stripe ``2n-1-rank >= n``). The second visible
      block is ``q_low @ kv_low`` when ``j < rank`` and
      ``q_high @ kv_high`` when ``j > rank`` — same shapes either way, so
      it is computed once on ``where``-selected inputs: no ``lax.cond``,
      no masked discards, perfectly balanced SPMD.

    Every step therefore runs exactly 2 stripe-sized flash blocks
    (vs 4 stripe-blocks per step for the contiguous ring): per-device
    attention work is ``(2n+1)`` stripe-blocks vs ``4n`` — the ~2x saving,
    verified by ``tests/test_attention.py::test_zigzag_halves_ring_flops``.

    The KV pair for step s+1 is ``ppermute``d before step s's flash calls,
    so the ICI transfer overlaps the compute (SURVEY.md §7.3).

    Inputs arrive in the ordinary contiguous layout ([batch, chunk, heads,
    head_dim], chunk ``2c`` = stripes ``2i, 2i+1``); the zigzag exchange in
    and out of stripe layout is two half-chunk ``ppermute``s each way.
    Requires an even local chunk. Differentiable end to end.
    """
    ring = axis_size(axis)
    head_dim = query.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    if ring == 1:
        out, _ = _attention_lse(query, key, value, causal=True, scale=scale,
                                inner=inner)
        return out
    assert query.shape[1] % 2 == 0, (
        f'zigzag ring needs an even local chunk, got {query.shape[1]}')
    rank = lax.axis_index(axis)
    permute = _ring_permute(axis, ring)

    q_low, q_high = _to_zigzag(query, axis, ring)
    k_low, k_high = _to_zigzag(key, axis, ring)
    v_low, v_high = _to_zigzag(value, axis, ring)
    kv = (k_low, k_high, v_low, v_high)

    # rotate before computing the diagonal so step 1's pair is in flight
    # under the three step-0 flash calls
    kv_next = jax.tree.map(permute, kv)

    # step 0: the diagonal of the device's own stripe pair
    out_low, lse_low = _attention_lse(q_low, k_low, v_low, causal=True,
                                      scale=scale, inner=inner)
    out_low = out_low.astype(jnp.float32)
    out_high, lse_high = _attention_lse(q_high, k_low, v_low, causal=False,
                                        scale=scale, inner=inner)
    out_high = out_high.astype(jnp.float32)
    part_out, part_lse = _attention_lse(q_high, k_high, v_high, causal=True,
                                        scale=scale, inner=inner)
    out_high, lse_high = _merge_lse(out_high, lse_high, part_out, part_lse)

    for step in range(1, ring):
        kv = kv_next
        if step + 1 < ring:
            kv_next = jax.tree.map(permute, kv)
        arriving_k_low, arriving_k_high, arriving_v_low, arriving_v_high = kv
        source = (rank - step) % ring   # rank whose stripe pair just arrived
        # block 1: q_high x kv_low — visible for every source (low stripes
        # precede all high stripes)
        part_out, part_lse = _attention_lse(
            q_high, arriving_k_low, arriving_v_low, causal=False, scale=scale,
            inner=inner)
        out_high, lse_high = _merge_lse(out_high, lse_high, part_out, part_lse)
        # block 2: the past-dependent block, computed once on selected
        # inputs — q_low x kv_low when the source is in the past,
        # q_high x kv_high when it is in the future
        past = source < rank
        query_sel = jnp.where(past, q_low, q_high)
        key_sel = jnp.where(past, arriving_k_low, arriving_k_high)
        value_sel = jnp.where(past, arriving_v_low, arriving_v_high)
        part_out, part_lse = _attention_lse(query_sel, key_sel, value_sel,
                                            causal=False, scale=scale,
                                            inner=inner)
        out_low, lse_low = _merge_lse(
            out_low, lse_low,
            jnp.where(past, part_out, 0), jnp.where(past, part_lse, NEG_INF))
        out_high, lse_high = _merge_lse(
            out_high, lse_high,
            jnp.where(past, 0, part_out), jnp.where(past, NEG_INF, part_lse))

    out = _from_zigzag(out_low.astype(query.dtype),
                       out_high.astype(query.dtype), axis, ring)
    return out


def ulysses_attention(query, key, value, *, axis: str = SEQ,
                      causal: bool = True, scale: float | None = None):
    """All-to-all sequence parallelism. Call inside ``shard_map``.

    Local [B, S/n, H, D] chunks are shard-transposed to [B, S, H/n, D]
    (full sequence, head subset), attended with the flash kernel, and
    transposed back.
    """
    ring = axis_size(axis)
    heads = query.shape[2]
    assert heads % ring == 0, (
        f'ulysses needs heads ({heads}) divisible by the seq axis ({ring})')

    def swap_in(tensor):   # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(tensor, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def swap_out(tensor):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(tensor, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    from tpusystem.ops.pallas.flash import flash_attention
    out = flash_attention(swap_in(query), swap_in(key), swap_in(value),
                          causal=causal, scale=scale)
    return swap_out(out)


def ring_self_attention(query, key, value, mesh, *, causal: bool = True,
                        variant: str = 'ring', inner: str = 'flash'):
    """Convenience wrapper: shard_map the chosen variant over ``mesh``.

    Inputs are global [B, S, H, D]; batch shards over (data, fsdp), sequence
    over seq. ``inner`` selects ring's per-chunk kernel ('flash'|'einsum').
    Useful standalone and as the reference harness for tests.

    ``variant='ring'`` auto-selects the balanced zigzag formulation for
    causal attention whenever the sequence splits into ``2 * seq_axis``
    stripes (the ~2x FLOPs saving — see :func:`zigzag_ring_attention`),
    falling back to the contiguous ring otherwise. ``'zigzag'`` forces it
    (raising when the shape cannot stripe); ``'ulysses'`` is the
    all-to-all variant.
    """
    seq_size = mesh.shape[SEQ]
    stripeable = (causal and seq_size > 0
                  and query.shape[1] % (2 * seq_size) == 0)
    if variant == 'zigzag':
        if not causal:
            raise ValueError('zigzag ring attention is causal-only; use '
                             "variant='ring' for non-causal")
        if not stripeable:
            raise ValueError(
                f'zigzag needs seq length {query.shape[1]} divisible by '
                f'2 * seq axis ({2 * seq_size})')
    if variant == 'ring' and stripeable:
        variant = 'zigzag'

    if variant == 'zigzag':
        implementation = functools.partial(zigzag_ring_attention, inner=inner)
    elif variant == 'ring':
        implementation = functools.partial(ring_attention, causal=causal,
                                           inner=inner)
    elif variant == 'ulysses':
        # ulysses shard-transposes the head axis, so grouped KV must be
        # broadcast up to the query head count first (the ring variants
        # keep it grouped — group-factor fewer ppermute bytes)
        from tpusystem.ops.attention import repeat_kv_heads
        key, value = repeat_kv_heads(query, key, value)
        implementation = functools.partial(ulysses_attention, causal=causal)
    else:
        raise ValueError(f'unknown variant {variant!r}; '
                         "expected 'ring', 'zigzag' or 'ulysses'")
    data_parallel = mesh.shape[DATA] * mesh.shape[FSDP]
    # batch shards over (data, fsdp) when divisible (e.g. module.init traces
    # with batch 1 — replicate batch there, shard only the sequence)
    batch_axes = (DATA, FSDP) if query.shape[0] % data_parallel == 0 else None
    spec = P(batch_axes, SEQ, None, None)

    # check_vma=False: the flash pallas_call inside carries no
    # varying-mesh-axis info for the replication checker
    @functools.partial(
        shard_map, mesh=mesh, check_vma=False,
        in_specs=(spec, spec, spec), out_specs=spec)
    def mapped(q, k, v):
        return implementation(q, k, v)

    return mapped(query, key, value)
