"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability (SURVEY.md §2.4/§5): queries stay put while K/V
chunks rotate around the ICI ring via ``ppermute``; each device accumulates
blockwise-softmax partial results, so a sequence of length S costs each
device O(S/n) memory and the full S^2 attention FLOPs are spread n ways.

Two variants:

* :func:`ring_attention` — the ppermute ring, callable **inside**
  ``shard_map`` on seq-sharded [B, S/n, H, D] chunks. Differentiable
  (``ppermute`` has a transpose rule), so ``jax.grad`` works through it.
* :func:`ulysses_attention` — the all-to-all head/sequence swap (DeepSpeed
  Ulysses): transposes shards so each device holds *all* positions for a
  subset of heads, runs dense/flash attention locally, swaps back. Cheaper
  collectives for moderate contexts; requires heads % ring_size == 0.

The outer convenience :func:`ring_self_attention` wires the ``shard_map``
over a mesh for both.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.ops.attention import NEG_INF, causal_mask
from tpusystem.parallel.mesh import DATA, FSDP, SEQ


def _chunk_scores(query, key, scale, q_offset, kv_offset, causal):
    """Masked f32 scores for one (q-chunk, kv-chunk) pair."""
    scores = jnp.einsum('bqhd,bkhd->bhqk', query, key,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = causal_mask(query.shape[1], key.shape[1],
                           offset=q_offset - kv_offset)
        scores = jnp.where(mask, scores, NEG_INF)
    return scores


def ring_attention(query, key, value, *, axis: str = SEQ, causal: bool = True,
                   scale: float | None = None):
    """Blockwise ring attention. Call inside ``shard_map``.

    Args:
        query/key/value: local chunks [batch, chunk, heads, head_dim] of a
            sequence sharded over ``axis``.
    Returns:
        local output chunk [batch, chunk, heads, head_dim].
    """
    ring = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    chunk = query.shape[1]
    head_dim = query.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    q_offset = rank * chunk

    batch, _, heads, _ = query.shape
    running_max = jnp.full((batch, heads, chunk, 1), NEG_INF, jnp.float32)
    running_sum = jnp.zeros((batch, heads, chunk, 1), jnp.float32)
    accumulator = jnp.zeros((batch, chunk, heads, head_dim), jnp.float32)

    def permute(tensor):
        size = lax.axis_size(axis)
        return lax.ppermute(
            tensor, axis,
            [(source, (source + 1) % size) for source in range(size)])

    for step in range(ring):
        owner = (rank - step) % ring          # whose chunk we currently hold
        kv_offset = owner * chunk
        scores = _chunk_scores(query, key, scale, q_offset, kv_offset, causal)
        chunk_max = jnp.max(scores, axis=-1, keepdims=True)
        new_max = jnp.maximum(running_max, chunk_max)
        probs = jnp.exp(scores - new_max)
        correction = jnp.exp(running_max - new_max)
        running_sum = running_sum * correction + jnp.sum(probs, -1, keepdims=True)
        partial = jnp.einsum('bhqk,bkhd->bqhd', probs.astype(value.dtype), value,
                             preferred_element_type=jnp.float32)
        accumulator = (accumulator
                       * correction.transpose(0, 2, 1, 3)
                       + partial)
        running_max = new_max
        if step != ring - 1:
            key = permute(key)
            value = permute(value)

    safe_sum = jnp.where(running_sum == 0.0, 1.0, running_sum)
    normalized = accumulator / safe_sum.transpose(0, 2, 1, 3)
    return normalized.astype(query.dtype)


def ulysses_attention(query, key, value, *, axis: str = SEQ,
                      causal: bool = True, scale: float | None = None):
    """All-to-all sequence parallelism. Call inside ``shard_map``.

    Local [B, S/n, H, D] chunks are shard-transposed to [B, S, H/n, D]
    (full sequence, head subset), attended densely, and transposed back.
    """
    ring = lax.axis_size(axis)
    heads = query.shape[2]
    assert heads % ring == 0, (
        f'ulysses needs heads ({heads}) divisible by the seq axis ({ring})')

    def swap_in(tensor):   # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(tensor, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def swap_out(tensor):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(tensor, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    from tpusystem.ops.attention import dot_product_attention
    out = dot_product_attention(swap_in(query), swap_in(key), swap_in(value),
                                causal=causal, scale=scale)
    return swap_out(out)


def ring_self_attention(query, key, value, mesh, *, causal: bool = True,
                        variant: str = 'ring'):
    """Convenience wrapper: shard_map the chosen variant over ``mesh``.

    Inputs are global [B, S, H, D]; batch shards over (data, fsdp), sequence
    over seq. Useful standalone and as the reference harness for tests.
    """
    implementation = {'ring': ring_attention, 'ulysses': ulysses_attention}[variant]
    data_parallel = mesh.shape[DATA] * mesh.shape[FSDP]
    # batch shards over (data, fsdp) when divisible (e.g. module.init traces
    # with batch 1 — replicate batch there, shard only the sequence)
    batch_axes = (DATA, FSDP) if query.shape[0] % data_parallel == 0 else None
    spec = P(batch_axes, SEQ, None, None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec)
    def mapped(q, k, v):
        return implementation(q, k, v, causal=causal)

    return mapped(query, key, value)
