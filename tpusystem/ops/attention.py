"""Attention ops.

The XLA-first implementation: plain einsum attention that the compiler fuses
and tiles onto the MXU, with softmax accumulated in float32 regardless of the
activation dtype (bf16-safe). The Pallas flash kernel
(:mod:`tpusystem.ops.pallas.flash`) and the ring/sequence-parallel variant
(:mod:`tpusystem.ops.ring`) plug in behind the same signature.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def causal_mask(query_length: int, key_length: int,
                *, offset: int | jax.Array = 0) -> jax.Array:
    """Boolean [q, k] mask where True = attend. ``offset`` is the position of
    the first query relative to the first key (used by ring attention blocks;
    may be a traced value such as ``rank * chunk``)."""
    query_positions = jnp.arange(query_length)[:, None] + offset
    key_positions = jnp.arange(key_length)[None, :]
    return query_positions >= key_positions


def repeat_kv_heads(query, key, value):
    """Broadcast grouped KV heads up to the query head count (Llama-3 GQA).
    The single implementation behind every attention kernel."""
    query_heads, kv_heads = query.shape[2], key.shape[2]
    if kv_heads == query_heads:
        return key, value
    assert query_heads % kv_heads == 0, (
        f'query heads ({query_heads}) must be a multiple of KV heads '
        f'({kv_heads}) for grouped-query attention')
    group = query_heads // kv_heads
    return jnp.repeat(key, group, axis=2), jnp.repeat(value, group, axis=2)


def attend(query, key, value, *, kernel: str = 'xla', mesh=None,
           causal: bool = True, dropout: float = 0.0, dropout_rng=None):
    """Kernel dispatch shared by the model families.

    ``'xla'`` routes to :func:`dot_product_attention` (GSPMD-shardable,
    GQA-aware, optional probability dropout). ``'flash'`` is the Pallas
    O(seq)-memory kernel — single-shard when ``mesh`` is None, composed
    with DP/FSDP/TP via ``shard_map`` over the (data, fsdp) x model axes
    when a mesh is passed; attention-probability dropout runs in-kernel
    (positional hash masks regenerated in the backward).
    ``'ring'``/``'ulysses'`` are the sequence-parallel variants (need
    ``mesh`` with a seq axis); grouped KV stays grouped on the ring
    variants (group-factor fewer ppermute bytes, KV shared across each
    query-head group by the flash inner kernel) and is broadcast only for
    ulysses, whose all_to_all splits the head axis; probability dropout
    is not implemented there.
    """
    if kernel == 'xla':
        return dot_product_attention(query, key, value, causal=causal,
                                     dropout=dropout, dropout_rng=dropout_rng)
    if kernel == 'flash':  # flash broadcasts GQA heads itself
        from tpusystem.ops.pallas.flash import (flash_attention,
                                                sharded_flash_attention)
        if mesh is not None:  # compose with DP/FSDP/TP via shard_map
            return sharded_flash_attention(query, key, value, mesh,
                                           causal=causal, dropout=dropout,
                                           dropout_rng=dropout_rng)
        return flash_attention(query, key, value, causal=causal,
                               dropout=dropout, dropout_rng=dropout_rng)
    if dropout:
        raise ValueError("attention-probability dropout is only implemented "
                         f"on the 'xla' and 'flash' kernels, not {kernel!r}")
    if kernel in ('ring', 'ulysses'):
        from tpusystem.ops.ring import ring_self_attention
        # grouped KV stays grouped on the ring: the rotating ppermutes then
        # move group-factor fewer bytes and the flash inner kernel shares
        # KV across each query-head group itself (ulysses repeats inside
        # ring_self_attention — its all_to_all splits the head axis)
        if mesh is None:
            raise ValueError(
                f'{kernel!r} attention needs a mesh with a seq axis '
                '(pass mesh=... to the model)')
        return ring_self_attention(query, key, value, mesh,
                                   causal=causal, variant=kernel)
    raise ValueError(f'unknown attention kernel {kernel!r}; '
                     "expected 'xla', 'flash', 'ring' or 'ulysses'")


def _debug_cache_enabled() -> bool:
    """Opt-in runtime verification of decode-cache contracts
    (``TPUSYSTEM_DEBUG_CACHE=1``); read per trace so tests can flip it.

    **Trace time, not run time**: the flag decides whether the check is
    baked into the program, so already-compiled decode programs keep the
    setting they were traced with. Set the env var before the first
    ``generate`` call (or ``jax.clear_caches()`` to force a retrace) —
    flipping it mid-process does not arm checks in cached executables.
    """
    import os
    return os.environ.get('TPUSYSTEM_DEBUG_CACHE', '') == '1'


def _assert_uniform_cursor(cursor):
    """Host-side check behind :func:`_debug_cache_enabled`: the
    ``per_row=False`` fast path writes every row's KV at ``cursor[0]``."""
    import numpy as np
    cursor = np.asarray(cursor)
    if (cursor != cursor[0]).any():
        raise ValueError(
            f'cached_attention(per_row=False) requires a uniform cache '
            f'cursor, got {cursor!r}; pass per_row=True for externally '
            'managed or speculative cursor state')


def paged_attention(module, query, key, value, max_seq: int,
                    pages: tuple[int, int]):
    """Incremental attention over a **paged** KV cache (block pool +
    per-row block tables) — the serving engine's layout
    (:mod:`tpusystem.serve`, vLLM's PagedAttention block-table idea on
    the :func:`cached_attention` machinery).

    ``pages = (num_blocks, block_size)``. Instead of each row owning a
    contiguous ``[max_seq, heads, head_dim]`` strip, the cache is one
    shared pool of ``num_blocks`` blocks of ``block_size`` tokens
    (``'key'``/``'value'`` cache variables, flattened to
    ``[num_blocks * block_size, kv_heads, head_dim]``), and each row
    maps its *logical* block ``j`` (tokens ``j*block_size ...``) to a
    physical block through a ``'table'`` cache variable
    (``[batch, max_seq // block_size]`` int32). A sequence's cache can
    then live in non-contiguous blocks, and batch-row membership changes
    are host-side table edits plus block writes — never a reshape of the
    pool, so the engine's decode program compiles once.

    Contract (owned by :class:`tpusystem.serve.Engine`): physical block
    0 is the **trash block** — every unmapped table entry points there,
    so retired rows' dead writes land in trash instead of a live row's
    blocks; distinct live rows never share a physical block; the table
    rows for a sequence are populated (host-side) before its cursor
    advances into them. Cursors are inherently per-row (the ``index``
    cursor leaf is the same ``[batch]`` int32 the contiguous per-row
    path uses, so :mod:`tpusystem.train.cursors` edits apply
    unchanged).

    Reads are bucketed like the contiguous path, in block units: the
    smallest power-of-2 block window covering the deepest filled row is
    gathered from the pool (``lax.switch`` over static widths — one
    compiled program, capacity-independent read cost), masked at each
    row's own depth. Masked positions contribute exact zeros, so a row's
    output is independent of its co-batched traffic in
    window-length-invariant arithmetic (f32; the same caveat as
    speculative verify applies at the TPU MXU's default precision).
    """
    num_blocks, block = pages
    if max_seq % block:
        raise ValueError(f'max_seq ({max_seq}) must be a multiple of the '
                         f'page block_size ({block})')
    batch, length, kv_heads, head_dim = key.shape
    max_blocks = max_seq // block
    pool_shape = (num_blocks * block, kv_heads, head_dim)
    cache_key = module.variable('cache', 'key', jnp.zeros, pool_shape,
                                key.dtype)
    cache_value = module.variable('cache', 'value', jnp.zeros, pool_shape,
                                  value.dtype)
    table = module.variable('cache', 'table', jnp.zeros,
                            (batch, max_blocks), jnp.int32)
    index = module.variable('cache', 'index',
                            lambda: jnp.zeros((batch,), jnp.int32))
    if module.is_initializing():
        return dot_product_attention(query, key, value, causal=True)
    cursor = index.value                                        # [batch]
    positions = cursor[:, None] + jnp.arange(length)[None, :]   # [B, L]
    # physical token slot of each logical position, through the table;
    # past-capacity positions clamp onto the last table column — the
    # engine keeps those columns unmapped (trash), so overflow writes
    # are dead, never corrupting (the generate() capacity contract)
    logical = jnp.minimum(positions // block, max_blocks - 1)
    physical = jnp.take_along_axis(table.value, logical, axis=1)
    slots = (physical * block + positions % block).reshape(-1)  # [B*L]
    cache_key.value = cache_key.value.at[slots].set(
        key.reshape(-1, kv_heads, head_dim).astype(cache_key.value.dtype))
    cache_value.value = cache_value.value.at[slots].set(
        value.reshape(-1, kv_heads, head_dim).astype(cache_value.value.dtype))
    index.value = cursor + length

    # bucketed block-window read: gather the first `width` table columns'
    # blocks and mask at each row's logical depth — the cached_attention
    # bucket discipline, in block units (same starting point: the
    # smallest window is ~256 tokens, or the whole table when smaller)
    def attend_over(width: int):
        def run():
            mapped = jax.lax.slice_in_dim(table.value, 0, width, axis=1)
            tokens = (mapped[:, :, None] * block
                      + jnp.arange(block)[None, None, :]
                      ).reshape(batch, width * block)
            keys = jnp.take(cache_key.value, tokens, axis=0)
            values = jnp.take(cache_value.value, tokens, axis=0)
            mask = (jnp.arange(width * block)[None, None, :]
                    <= positions[:, :, None])                  # [B, L, W]
            return dot_product_attention(query, keys, values,
                                         causal=False, mask=mask[:, None])
        return run

    # the contiguous path starts its buckets at 256 tokens (a slice is
    # nearly free, so fine-grained switching buys little); the paged
    # read is a GATHER whose cost is proportional to the window, so it
    # starts at 64 tokens — shallow rows read 4x less pool
    buckets = [min(max_blocks, max(1, 64 // block))]
    while buckets[-1] < max_blocks:
        buckets.append(min(2 * buckets[-1], max_blocks))
    if len(buckets) == 1:
        return attend_over(max_blocks)()
    filled_blocks = (jnp.max(positions) + block) // block
    bucket_index = sum((filled_blocks > width).astype(jnp.int32)
                       for width in buckets[:-1])
    return jax.lax.switch(bucket_index, [attend_over(w) for w in buckets])


def cached_attention(module, query, key, value, max_seq: int,
                     per_row: bool = False, pages: tuple | None = None):
    """Incremental (KV-cache) attention for autoregressive decoding.

    Called from inside a flax module in decode mode: maintains
    ``key``/``value``/``index`` variables in the ``'cache'`` collection
    (apply with ``mutable=['cache']``), appends this call's KV at the
    cache cursor, and attends the new queries over every filled position.
    KV is cached at its own head count — grouped-query broadcast happens
    inside :func:`dot_product_attention` — so the cache stays small under
    GQA. The single implementation behind both LM families' decode paths.

    Capacity contract: the caller keeps cumulative tokens within
    ``max_seq`` (:func:`tpusystem.train.generate` enforces it up front).
    Past capacity the cursor is a traced value, so no in-program error is
    possible — out-of-bounds scatter rows are silently dropped (the new
    K/V is never written and attention reads stale/zero positions).

    The cursor (``index``) is **per-row** — ``[batch]`` int32 — so rows
    may sit at different depths: speculative decoding advances each
    sequence by its own acceptance count instead of the batch minimum
    (``per_row=True``). Ordinary decode keeps every row equal, and with
    ``per_row=False`` (default) the cache write uses a single
    ``dynamic_update_slice`` at the shared cursor instead of a
    computed-2D-index scatter — on TPU the scatter in the per-token hot
    loop is the slower lowering. The caller owns the uniformity guarantee
    (``tpusystem.train.generate`` passes ``per_row`` only on the
    speculative path): any externally managed cursor state that may
    diverge per row — e.g. a cache left behind by a speculative run —
    **must** use ``per_row=True``, or rows whose cursor differs from row
    0 are silently corrupted. Set ``TPUSYSTEM_DEBUG_CACHE=1`` to verify
    the contract at runtime: a host callback checks cursor uniformity on
    every cached step and fails on violation — directly as the
    ``ValueError`` in eager code, or (inside ``jit``, where callbacks run
    async) as a callback-failure ``XlaRuntimeError`` at the next sync
    whose log carries the message. Debug-only — it forces a per-step
    host transfer.

    ``pages=(num_blocks, block_size)`` switches the cache to the paged
    block-pool layout (:func:`paged_attention` — the serving engine's
    non-contiguous per-row storage; implies per-row cursors).
    """
    if pages is not None:
        return paged_attention(module, query, key, value, max_seq, pages)
    batch, length, kv_heads, head_dim = key.shape
    if length > max_seq:
        # static shapes let this raise at trace time; per-step overflow
        # (cumulative tokens, a traced cursor) is the caller's contract —
        # tpusystem.train.generate enforces it up front
        raise ValueError(
            f'prompt length {length} exceeds the KV cache capacity '
            f'max_seq={max_seq}; raise max_seq or truncate the prompt')
    # Prefill is the call that creates the cache variables: detect it
    # before declaring them, so the prompt can attend over just its own
    # fresh K/V (causal) instead of the max_seq-wide zero-padded cache —
    # at Llama's max_seq=8192 a 128-token prompt would otherwise build
    # 64x oversized score tensors, all masked away.
    prefill = not module.has_variable('cache', 'index')
    cache_shape = (batch, max_seq, kv_heads, head_dim)
    cache_key = module.variable('cache', 'key', jnp.zeros, cache_shape, key.dtype)
    cache_value = module.variable('cache', 'value', jnp.zeros, cache_shape,
                                  value.dtype)
    index = module.variable('cache', 'index',
                            lambda: jnp.zeros((batch,), jnp.int32))
    if module.is_initializing():
        return dot_product_attention(query, key, value, causal=True)
    cursor = index.value                                    # [batch]
    positions = cursor[:, None] + jnp.arange(length)[None, :]   # [B, L]
    if per_row:
        rows = jnp.arange(batch)[:, None]
        cache_key.value = cache_key.value.at[rows, positions].set(
            key.astype(cache_key.value.dtype))
        cache_value.value = cache_value.value.at[rows, positions].set(
            value.astype(cache_value.value.dtype))
    else:
        if _debug_cache_enabled():
            jax.debug.callback(_assert_uniform_cursor, cursor)
        # uniform cursor: one dynamic_update_slice writes every row at the
        # shared offset (cursor[0] — the caller's uniformity contract).
        # Past-capacity behavior diverges from the scatter path: the slice
        # start clamps so the write lands at max_seq - length instead of
        # being dropped — both are inside the caller's capacity contract.
        start = cursor[0]
        cache_key.value = jax.lax.dynamic_update_slice(
            cache_key.value, key.astype(cache_key.value.dtype),
            (0, start, 0, 0))
        cache_value.value = jax.lax.dynamic_update_slice(
            cache_value.value, value.astype(cache_value.value.dtype),
            (0, start, 0, 0))
    index.value = cursor + length
    if prefill:
        # Long prompts route through the flash kernel: einsum attention
        # materializes the [B, H, L, L] scores tensor — at Llama's
        # max_seq=8192 that is exactly the allocation flash exists to
        # avoid, paid once per generation. flash_attention falls back to
        # the einsum path itself when the length cannot tile, so short
        # prompts lose nothing.
        if length >= 512:
            from tpusystem.ops.pallas.flash import flash_attention
            return flash_attention(query, key, value, causal=True)
        return dot_product_attention(query, key, value, causal=True)
    # attend causally over the filled prefix, per row (key position <=
    # row cursor + query offset). The cache is allocated max_seq wide,
    # but reading all of it every step makes decode cost scale with
    # *capacity*, not fill: at 125M/batch 8 the full-width read is ~2.3
    # of the 3.4 ms step at max_seq 1024 (benchmarks/decode_roofline.py).
    # Bucketed attention reads only the smallest power-of-2 window
    # covering the filled prefix — lax.switch over static slice widths,
    # so shapes stay static per branch inside one compiled program.
    def attend_over(width: int):
        def run():
            keys = jax.lax.slice_in_dim(cache_key.value, 0, width, axis=1)
            values = jax.lax.slice_in_dim(cache_value.value, 0, width, axis=1)
            mask = (jnp.arange(width)[None, None, :]
                    <= positions[:, :, None])              # [B, L, W]
            return dot_product_attention(query, keys, values,
                                         causal=False, mask=mask[:, None])
        return run

    buckets = [256]
    while buckets[-1] < max_seq:
        buckets.append(min(2 * buckets[-1], max_seq))
    if len(buckets) == 1:
        return attend_over(max_seq)()
    filled = jnp.max(positions) + 1
    # NOT named `index`: that would shadow the flax cache variable of the
    # same name assigned above and invite silent misuse of the cursor
    bucket_index = sum((filled > width).astype(jnp.int32)
                       for width in buckets[:-1])
    return jax.lax.switch(bucket_index, [attend_over(w) for w in buckets])


def dot_product_attention(query, key, value, *, causal: bool = True,
                          mask=None, scale: float | None = None,
                          dropout: float = 0.0, dropout_rng=None):
    """Multi-head attention over [batch, length, heads, head_dim] tensors.

    Softmax runs in float32; output returns in the input dtype. Supports
    grouped-query attention: when ``key``/``value`` carry fewer heads than
    ``query``, KV heads are broadcast over query-head groups (Llama-3 GQA).
    ``dropout`` > 0 (with ``dropout_rng``) drops attention probabilities.
    """
    input_dtype = query.dtype
    head_dim = query.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    key, value = repeat_kv_heads(query, key, value)

    scores = jnp.einsum('bqhd,bkhd->bhqk', query, key,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        scores = jnp.where(causal_mask(query.shape[1], key.shape[1]),
                           scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    if dropout > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout, weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout), 0.0)
    return jnp.einsum('bhqk,bkhd->bqhd', weights.astype(input_dtype), value)
