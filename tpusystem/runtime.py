"""Per-host runtime: world, control plane, and buses in one facade.

The reference's composition root is a single-process ``main.py``
(``examples/tinysys/main.py``); a TPU pod runs that composition root once
per host. :class:`Runtime` is the object that makes the same ``main()``
correct in both worlds:

* joins the multi-host job (``jax.distributed``-style) when a coordinator
  is configured, stays single-process otherwise;
* brings up the control plane (:mod:`tpusystem.parallel.multihost`) — the
  primary host doubles as the :class:`~tpusystem.parallel.multihost.Hub`;
* exposes :class:`~tpusystem.parallel.multihost.DistributedProducer` /
  ``DistributedPublisher`` buses with rank-aware consumer placement, so
  storage/TensorBoard consumers register ``primary_only`` and run exactly
  once per experiment (SURVEY.md §5);
* optionally hash-chains the event stream
  (:class:`~tpusystem.observe.EventLedger`) for cross-host divergence
  detection;
* owns the epoch-boundary housekeeping — :meth:`sync` drains remote
  events and verifies the ledger; :meth:`should_stop` turns one host's
  stop wish into everyone's verdict before the next collective.

Typical pod-ready epoch loop::

    runtime = Runtime(preemption=True)        # env-driven; Loopback off-pod
    runtime.producer.register(logging_consumer())
    runtime.producer.register(tracking_consumer(), primary_only=True)
    runtime.producer.register(checkpoint_consumer())   # ALL hosts: saves are collective
    runtime.producer.register(recovery_consumer())     # WorkerLost -> restart
    try:
        for epoch in range(epochs):
            try:
                service.handle('iterate', model, loaders, metrics)
                wants_stop = False
            except StopIteration:  # unhandled stop event unwound from commit
                wants_stop = True
            runtime.sync()         # Preempted / WorkerLostError raise here
            if runtime.should_stop(wants_stop):
                break
    except (Preempted, WorkerLostError) as reason:
        repository.store(model)                # emergency checkpoint
        repository.fence(model)                # durability receipt
        raise exit_for_restart(reason)         # scheduler restarts -> resume
    finally:
        runtime.close()

The launcher side of that contract — relaunch on 42/43 with backoff,
crash-loop containment, SIGTERM forwarding into the preemption handler,
and hot in-memory restores — is :class:`tpusystem.parallel.Supervisor`;
run the worker under it and the ``raise exit_for_restart(...)`` above is
answered in seconds.
"""

from __future__ import annotations

import os
import signal as signal_module

from tpusystem.observe.ledger import EventLedger
from tpusystem.parallel import multihost
from tpusystem.parallel.multihost import (
    DistributedProducer, DistributedPublisher, Hub, Loopback, TcpTransport,
    World,
)
from tpusystem.parallel.recovery import Preempted


def _control_address(coordinator: str | None,
                     control_port: int | None) -> tuple[str, int]:
    """Resolve where the control-plane hub lives for a multi-host job.

    Precedence: ``TPUSYSTEM_CONTROL=host:port`` env var; else the
    coordinator's host with ``control_port`` (or the coordinator port + 1).
    There is deliberately no localhost fallback — every host dialing its own
    loopback would "work" single-host and silently partition a pod.
    """
    spec = os.environ.get('TPUSYSTEM_CONTROL')
    if spec:
        return _parse_hostport(spec, 'TPUSYSTEM_CONTROL')
    return _coordinator_derived(coordinator, control_port)


def _parse_hostport(spec: str, source: str) -> tuple[str, int]:
    host, separator, port = spec.rpartition(':')
    if not separator:
        raise ValueError(f'{source} must be host:port, got {spec!r}')
    return host, int(port)


def _deputy_address() -> tuple[str, int] | None:
    """``TPUSYSTEM_CONTROL_DEPUTY=host:port`` enables hub redundancy: rank 1
    hosts a standby hub there and every transport fails over to it if the
    primary hub's host dies (see ``multihost.connect``)."""
    spec = os.environ.get('TPUSYSTEM_CONTROL_DEPUTY')
    if not spec:
        return None
    return _parse_hostport(spec, 'TPUSYSTEM_CONTROL_DEPUTY')


def _coordinator_derived(coordinator: str | None,
                         control_port: int | None) -> tuple[str, int]:
    if coordinator:
        host, separator, port = coordinator.rpartition(':')
        if not separator:
            host, port = coordinator, None
        if control_port is not None:
            return host, control_port
        if port is not None:
            return host, int(port) + 1
    raise ValueError(
        'multi-host job without a control-plane address: set '
        'TPUSYSTEM_CONTROL=host:port, or pass coordinator="host:port" '
        '(control plane defaults to port+1)')


class Runtime:
    """Host-side runtime context for a (possibly multi-host) training job.

    Args:
        coordinator: ``host:port`` of the JAX coordinator, or None to read
            ``TPUSYSTEM_COORDINATOR`` from the environment; absent both, the
            job is single-process and the control plane is a
            :class:`Loopback`.
        control_port: TCP port for the control-plane hub on the primary
            host (default: coordinator port + 1).
        ledger: hash-chain the event stream for divergence detection
            (:meth:`sync` then verifies it across hosts).
        heartbeat: seconds between liveness pings; a host silent for 4
            intervals surfaces as a ``WorkerLost`` event on every other
            host. ``None`` disables failure detection.
        preemption: install the SIGTERM preemption handler
            (:meth:`install_preemption_handler`) at construction. Off by
            default — signal handlers can only be installed from the main
            thread, and not every embedding owns the process's signals.
    """

    def __init__(self, coordinator: str | None = None, *,
                 control_port: int | None = None,
                 num_processes: int | None = None,
                 process_id: int | None = None,
                 ledger: bool = False,
                 heartbeat: float | None = 10.0,
                 preemption: bool = False) -> None:
        coordinator = coordinator or os.environ.get('TPUSYSTEM_COORDINATOR')
        self._preempt_signal: int | None = None
        self._previous_handlers: dict = {}
        self.world: World = multihost.initialize(
            coordinator, num_processes, process_id)
        self.hub: Hub | None = None
        if self.world.process_count > 1:
            address = _control_address(coordinator, control_port)
            self.transport, self.hub = multihost.connect(
                address, self.world,
                heartbeat_interval=heartbeat,
                heartbeat_timeout=4 * heartbeat if heartbeat else None,
                deputy_address=_deputy_address())
        else:
            self.transport: Loopback | TcpTransport = Loopback()
        self.producer = DistributedProducer(self.transport)
        self.publisher = DistributedPublisher(self.transport)
        self.ledger: EventLedger | None = (
            EventLedger().tap(self.producer) if ledger else None)
        if preemption:
            self.install_preemption_handler()

    @property
    def is_primary(self) -> bool:
        return self.world.is_primary

    def install_preemption_handler(
            self, *signals: int) -> None:
        """Arm preemption detection: the given signals (default SIGTERM —
        what TPU-VM maintenance events and most schedulers deliver) set a
        flag, and the next :meth:`sync` raises
        :class:`~tpusystem.parallel.recovery.Preempted` on the host loop
        thread.

        The handler itself only records the signal: raising from inside a
        signal handler could land mid-collective or mid-save and tear
        exactly the state the emergency checkpoint needs intact. The raise
        happens at the :meth:`sync` drain point; when one epoch outlasts
        the scheduler's kill grace window, poll :attr:`preempted` inside
        the step loop and call :meth:`sync` when it trips (see
        :meth:`sync`). Must be called from the main thread (a Python
        signal-handling constraint); the previous handlers are restored by
        :meth:`close`.
        """
        if not signals:
            signals = (signal_module.SIGTERM,)

        def on_signal(signum, frame):
            self._preempt_signal = signum

        for signum in signals:
            previous = signal_module.signal(signum, on_signal)
            # a re-install must not record our own handler as 'previous',
            # or close() would leave it armed for the process's lifetime
            self._previous_handlers.setdefault(signum, previous)

    @property
    def preempted(self) -> bool:
        """True once a preemption signal arrived (sticky until the
        :class:`Preempted` raise hands control to the exit path)."""
        return self._preempt_signal is not None

    def sync(self) -> None:
        """Epoch-boundary housekeeping: deliver queued remote events on this
        thread, then (when enabled) verify the event hash-chain across
        hosts. Call once per epoch — never unconditionally per step. Raises
        :class:`~tpusystem.parallel.recovery.Preempted` (after the drain,
        so queued events still deliver) when a preemption signal arrived
        since the last sync.

        When an epoch outlasts the scheduler's SIGTERM→SIGKILL grace
        window, guard the inner loop with the cheap :attr:`preempted` flag
        so the raise still lands at a step boundary::

            if runtime.preempted:
                runtime.sync()        # raises Preempted now, drained
        """
        self.producer.drain()
        self.publisher.drain()
        if self.ledger is not None:
            self.ledger.verify(self.transport)
        if self._preempt_signal is not None:
            raise Preempted(self._preempt_signal)

    def should_stop(self, wants_stop: bool) -> bool:
        """Collective early-stop verdict: any host wanting out stops all
        (the distributed form of the reference's exception-unwinding stop,
        ``torchsystem/domain/events.py:162-163``)."""
        return multihost.agree(self.transport, wants_stop, op='or')

    def barrier(self, timeout: float | None = None) -> None:
        """Host-level rendezvous (checkpoint commit points etc.).

        ``timeout`` (seconds, default the transport's 300 s) bounds the
        wait: a peer that died or hung *between* sync points — past the
        heartbeat detector but before its next contribution — surfaces as
        :class:`~tpusystem.parallel.multihost.CollectiveTimeout` (a
        ``ControlPlaneFailover``) instead of hanging this host forever.
        Handle it like a worker loss: checkpoint-fence and
        ``exit_for_restart``.
        """
        if timeout is None:
            self.transport.barrier()
        else:
            self.transport.barrier(timeout=timeout)

    def close(self) -> None:
        try:
            for signum, handler in self._previous_handlers.items():
                signal_module.signal(signum, handler)
            self._previous_handlers.clear()
        except ValueError:
            # close() on a non-main thread cannot touch signal dispositions
            # (a Python constraint); never let that abort the transport/hub
            # teardown below — the handler stays until the process exits
            pass
        self.transport.close()
        if self.hub is not None:
            self.hub.close()

    def __enter__(self) -> 'Runtime':
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
