"""tpusystem — TPU-native, message-driven training framework.

The architecture of mapache-software/torch-system (aggregates, domain
events, dependency injection, service buses, entity registry) rebuilt
TPU-first: pure jitted step functions over parameter pytrees, GSPMD
sharding on explicit device meshes, Pallas kernels for the hot ops, and a
control-plane bus that spans multi-host TPU pods.
"""

from tpusystem.compiler import Compiler
from tpusystem.depends import Depends, Provider
from tpusystem.domain import Aggregate, Event, Events
from tpusystem.runtime import Runtime

__version__ = '0.1.0'

__all__ = ['Aggregate', 'Compiler', 'Depends', 'Provider', 'Event', 'Events',
           'Runtime']
