"""Async sharded pytree checkpointer (Orbax-backed), preemption-hardened.

Replaces the reference's ``torch.save(model.nn, f'{root}/{id}.pth')`` +
``load_state_dict`` pair (``examples/tinysys/tinysys/repository.py:13-17``)
with a TPU-appropriate design:

* **sharded**: each host writes only the array shards it owns, so an 8B
  model on a v5p-64 checkpoints at aggregate disk bandwidth instead of
  funnelling through one host;
* **async**: the save is snapshotted and committed in the background, so the
  training loop resumes immediately (the analogue of keeping the bus off the
  hot path — SURVEY.md §7.3);
* **versioned by step**: one directory per identity, one step dir per
  version — historically one per *epoch*; with step-granular resume the
  version is any monotonic global step. :meth:`Checkpointer.latest` drives
  the reference's create-or-resume decision
  (``.../services/compilation.py:41-57``);
* **preemption-safe**: a save may be torn mid-write by a kill — restore and
  latest :meth:`verify` every candidate step dir and *fall back* to the
  newest committed one (logging what was discarded) instead of crashing on
  a truncated directory; :meth:`fence` records the newest committed step in
  a monotonic commit-fence file, the durability receipt an emergency
  (SIGTERM) checkpoint needs before the process exits.

Host-side resume metadata — the data-loader cursor, wall-clock, anything
JSON-able — rides each step as ``extras`` (:meth:`save` /
:meth:`extras`): device arrays go through Orbax, the cursor through an
atomically-renamed sidecar, and :meth:`resume` returns both.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pathlib
import shutil
import time
from typing import Any

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger('tpusystem.checkpoint')

# sidecar directories under {root}/{identity}; the leading dot keeps them
# out of Orbax's integer step scan
_EXTRAS_DIR = '.extras'
_FENCE_FILE = '.fence'


def abstract_like(tree: Any) -> Any:
    """Abstract pytree (shape/dtype/sharding) used as a restore target.

    Restoring onto the *current* mesh layout — not the layout at save time —
    is what makes checkpoints portable across topology changes (e.g. resume
    a v4-8 run on a v4-32).
    """
    def spec(leaf: Any) -> Any:
        if isinstance(leaf, jax.Array):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
        return leaf
    return jax.tree.map(spec, tree)


def _has_leaves(node: Any) -> bool:
    return bool(jax.tree.leaves(node))


def _shrink_empty_fields(node: Any) -> Any:
    """Image of a restore target without its leafless dataclass fields.

    A pytree dataclass that grew an *optional* field (``TrainState.health``,
    None when unused) no longer structure-matches checkpoints written
    before the field existed — Orbax compares tree keys, and the empty
    field still contributes one. This maps dataclass/struct nodes to plain
    dicts of their leaf-bearing fields (and prunes leafless dict entries),
    while sequences keep their exact type and arity — an optax chain tuple
    is saved as a list and must stay positional.
    """
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        return {field.name: _shrink_empty_fields(getattr(node, field.name))
                for field in dataclasses.fields(node)
                if _has_leaves(getattr(node, field.name))}
    if isinstance(node, dict):
        return {key: _shrink_empty_fields(value)
                for key, value in node.items() if _has_leaves(value)}
    if isinstance(node, (list, tuple)):
        rebuilt = [_shrink_empty_fields(value) for value in node]
        if hasattr(node, '_fields'):          # namedtuple (optax states)
            return type(node)(*rebuilt)
        return type(node)(rebuilt)
    return node


def _graft_restored(abstract: Any, image: Any) -> Any:
    """Reassemble ``abstract``'s structure from a shrunken-image restore:
    restored arrays land in their positions, pruned (leafless) fields keep
    the target's own value (e.g. ``health=None``)."""
    if dataclasses.is_dataclass(abstract) and not isinstance(abstract, type):
        fields = {}
        for field in dataclasses.fields(abstract):
            value = getattr(abstract, field.name)
            fields[field.name] = (_graft_restored(value, image[field.name])
                                  if _has_leaves(value) else value)
        return type(abstract)(**fields)
    if isinstance(abstract, dict):
        return {key: (_graft_restored(value, image[key])
                      if _has_leaves(value) else value)
                for key, value in abstract.items()}
    if isinstance(abstract, (list, tuple)):
        rebuilt = [_graft_restored(value, image[index])
                   for index, value in enumerate(abstract)]
        if hasattr(abstract, '_fields'):
            return type(abstract)(*rebuilt)
        return type(abstract)(rebuilt)
    return image


def _atomic_write(path: pathlib.Path, text: str) -> None:
    """Write-then-rename so readers never see a torn file (the same
    atomicity discipline Orbax applies to whole step dirs)."""
    path.parent.mkdir(parents=True, exist_ok=True)
    staging = path.with_name(path.name + '.tmp')
    staging.write_text(text)
    os.replace(staging, path)


class Checkpointer:
    """Identity-keyed, step-versioned pytree store.

    Layout: ``{root}/{identity}/{step}/...`` — the identity is the registry
    hash of the aggregate (deterministic across hosts and restarts), so every
    worker independently computes the same directory and the restore decision
    needs no coordination.
    """

    def __init__(self, root: str | pathlib.Path, *, max_to_keep: int | None = 3,
                 keep_every: int | None = None,
                 async_save: bool = True, save_retries: int = 2,
                 retry_backoff: float = 0.5, tracer: Any = None) -> None:
        """``max_to_keep`` bounds the rolling window; ``keep_every`` pins
        every Nth step forever in addition (GC policy: a long run keeps
        recent checkpoints for resume plus periodic ones for analysis
        /rollback instead of losing all history to the window).
        ``save_retries`` bounds the retry loop a flaky filesystem gets
        before :meth:`save` gives up (exponential backoff starting at
        ``retry_backoff`` seconds). ``tracer`` (an
        :class:`~tpusystem.observe.Tracer`, default None = no tracing
        work) wraps every save/restore dispatch in a span, so checkpoint
        cost shows on the same timeline as the recoveries it bounds."""
        self.root = pathlib.Path(root).absolute()
        self.max_to_keep = max_to_keep
        self.keep_every = keep_every
        self.async_save = async_save
        self.save_retries = save_retries
        self.retry_backoff = retry_backoff
        self.tracer = tracer
        self._managers: dict[str, ocp.CheckpointManager] = {}

    def _span(self, name: str, identity: str, epoch: Any):
        """A tracing span around one checkpoint operation (nullcontext
        when tracing is off — the default costs nothing)."""
        if self.tracer is None:
            import contextlib
            return contextlib.nullcontext()
        return self.tracer.span(name, cat='checkpoint',
                                args={'identity': identity, 'epoch': epoch})

    def _manager(self, identity: str) -> ocp.CheckpointManager:
        if identity not in self._managers:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=self.max_to_keep,
                keep_period=self.keep_every,
                enable_async_checkpointing=self.async_save)
            self._managers[identity] = ocp.CheckpointManager(
                self.root / identity, options=options)
        return self._managers[identity]

    def save(self, identity: str, epoch: int, state: Any, *,
             extras: Any | None = None) -> None:
        """Snapshot ``state`` under (identity, epoch); returns immediately.

        ``epoch`` is the version number — an epoch index or a global step;
        versions must be saved in increasing order. With ``async_save`` the
        device buffers are copied out synchronously (cheap) and serialized in
        a background thread; call :meth:`wait` (or rely on save-on-next-epoch
        barriers) before reading the files, and :meth:`fence` for a
        durability receipt.

        ``extras`` is optional host-side resume metadata (anything
        JSON-able: the data-loader cursor, host step, wall time). It is
        written synchronously to an atomically-renamed sidecar — it never
        blocks on the array serialization — and comes back via
        :meth:`extras` / :meth:`resume`.

        Failure surfacing: a *previous* async save that failed in the
        background raises here (and at :meth:`newest`) instead of hiding
        until :meth:`wait`/:meth:`fence` — the training loop learns its
        durability story broke at the very next step, while the state that
        could re-save is still alive. The save itself gets a bounded
        retry with exponential backoff (``save_retries`` / ``retry_backoff``)
        against transient filesystem errors before giving up.
        """
        self._surface_async_errors(identity)
        with self._span('checkpoint-save', identity, epoch):
            if extras is not None:
                # sidecar BEFORE the array commit: a kill between the two
                # must not leave a committed step with no cursor (an orphan
                # sidecar for a never-committed step is harmless, pruned
                # later)
                _atomic_write(self._extras_path(identity, epoch),
                              json.dumps(extras))
            manager = self._manager(identity)
            for attempt in range(self.save_retries + 1):
                try:
                    manager.save(epoch, args=ocp.args.StandardSave(state))
                    break
                except OSError as error:
                    if attempt == self.save_retries:
                        raise
                    delay = self.retry_backoff * (2 ** attempt)
                    logger.warning(
                        'checkpoint save %s/%s/%d failed (%s); retry %d/%d '
                        'in %.1fs', self.root, identity, epoch, error,
                        attempt + 1, self.save_retries, delay)
                    time.sleep(delay)
            self._prune_extras(identity)

    def _surface_async_errors(self, identity: str) -> None:
        """Re-raise a background async-save failure at the *next* call.

        Orbax parks exceptions from the commit thread until someone asks;
        without this probe they only surfaced at ``wait``/``fence`` —
        potentially thousands of steps after the durability story silently
        broke. Gated on the public ``check_for_errors`` where this Orbax
        has it."""
        manager = self._managers.get(identity)
        check = getattr(manager, 'check_for_errors', None)
        if check is not None:
            check()

    def _extras_path(self, identity: str, epoch: int) -> pathlib.Path:
        return self.root / identity / _EXTRAS_DIR / f'{int(epoch)}.json'

    def _prune_extras(self, identity: str) -> None:
        """Drop sidecars whose step dir Orbax's GC already collected.

        Only steps *below* the newest on-disk step are candidates: an async
        save still in flight has no committed dir yet (its tmp dir is not
        integer-named), and its sidecar — written synchronously — must
        survive until the commit lands."""
        extras_dir = self.root / identity / _EXTRAS_DIR
        if not extras_dir.is_dir():
            return
        on_disk = self._disk_steps(identity)
        if not on_disk:
            return
        live = set(on_disk)
        for sidecar in extras_dir.glob('*.json'):
            if not sidecar.stem.isdigit():
                continue
            step = int(sidecar.stem)
            if step < on_disk[-1] and step not in live:
                sidecar.unlink(missing_ok=True)

    def extras(self, identity: str, epoch: int) -> Any | None:
        """Host-side resume metadata saved with (identity, epoch), or None."""
        path = self._extras_path(identity, epoch)
        if not path.is_file():
            return None
        return json.loads(path.read_text())

    # ------------------------------------------------------------------
    # integrity: verify / committed steps / fence

    def _disk_steps(self, identity: str) -> list[int]:
        """Integer-named step dirs on disk, ascending — committed or not.

        Read from the filesystem (not the manager's cached step list) so a
        fresh process sees exactly what a kill left behind, including torn
        dirs a crashed writer never renamed away.
        """
        home = self.root / identity
        if not home.is_dir():
            return []
        return sorted(int(entry.name) for entry in home.iterdir()
                      if entry.is_dir() and entry.name.isdigit())

    def verify(self, identity: str, epoch: int) -> bool:
        """Integrity probe: is (identity, epoch) a *committed* checkpoint?

        A committed Orbax step dir carries a ``_CHECKPOINT_METADATA`` commit
        marker and at least one item payload with its ``_METADATA``
        manifest. A dir missing either is incomplete — a save torn by a
        preemption mid-write, or a partial copy — and must be skipped by
        the resume path, never handed to a restore that would crash on it.

        Orbax's public ``is_checkpoint_finalized`` is consulted where
        available but cannot replace the marker probe: on the pinned 0.7.0
        it only checks the commit-by-rename naming convention, so a
        planted/truncated dir with a plain integer name passes it.
        """
        step_dir = self.root / identity / str(int(epoch))
        if not step_dir.is_dir():
            return False
        is_tmp = getattr(ocp.utils, 'is_tmp_checkpoint', None)
        if is_tmp is not None and is_tmp(step_dir):
            return False
        if not (step_dir / '_CHECKPOINT_METADATA').is_file():
            return False
        items = [entry for entry in step_dir.iterdir() if entry.is_dir()]
        if not items:
            return False
        return all((item / '_METADATA').is_file() for item in items)

    def committed(self, identity: str) -> list[int]:
        """Committed (verified) steps for the identity, ascending; torn or
        corrupt step dirs are skipped and logged."""
        steps = []
        for step in self._disk_steps(identity):
            if self.verify(identity, step):
                steps.append(step)
            else:
                logger.warning(
                    'checkpoint %s/%s/%d is incomplete or corrupt; skipping',
                    self.root, identity, step)
        return steps

    def fence(self, identity: str) -> int | None:
        """Commit fence: block until in-flight saves land, then record the
        newest committed step in a monotonic fence file.

        The fence is the durability receipt of the preemption path — an
        emergency save followed by ``fence()`` guarantees the checkpoint is
        on disk before the process exits with a restartable code. The
        recorded step never decreases: a reader of :meth:`fenced` can trust
        that at least that step survived, whatever a later kill tore.
        """
        self.wait()
        steps = self.committed(identity)
        newest = steps[-1] if steps else None
        if newest is None:
            return self.fenced(identity)
        previous = self.fenced(identity)
        if previous is not None and previous > newest:
            return previous
        _atomic_write(self.root / identity / _FENCE_FILE,
                      json.dumps({'step': newest}))
        return newest

    def fenced(self, identity: str) -> int | None:
        """The fenced (guaranteed-durable) step, or None before any fence."""
        path = self.root / identity / _FENCE_FILE
        if not path.is_file():
            return None
        return int(json.loads(path.read_text())['step'])

    # ------------------------------------------------------------------
    # restore

    def restore(self, identity: str, target: Any, epoch: int | None = None) -> Any:
        """Restore the pytree saved under (identity, epoch or latest).

        ``target`` may be a concrete pytree (its shapes/dtypes/shardings are
        used, see :func:`abstract_like`) or an abstract one. Each shard is
        read straight onto its mesh device.

        An **explicit** ``epoch`` must exist and verify — a missing or
        corrupt one raises :class:`FileNotFoundError` naming the committed
        epochs, so the caller sees what it *can* restore instead of an
        opaque Orbax error. With ``epoch=None`` the newest committed step is
        used, falling back over torn/corrupt dirs (each discard logged).
        """
        abstract = abstract_like(target)
        with self._span('checkpoint-restore', identity, epoch):
            if epoch is not None:
                if not self.verify(identity, epoch):
                    available = self.committed(identity)
                    raise FileNotFoundError(
                        f'no committed checkpoint for identity {identity!r} '
                        f'at epoch {epoch} under {self.root} '
                        f'(committed epochs: {available or "none"})')
                return self._restore_step(identity, epoch, abstract)
            return self._restore_newest(identity, abstract)[0]

    def _restore_step(self, identity: str, epoch: int, abstract: Any) -> Any:
        """One step's restore, with the legacy-shape fallback.

        A target pytree that grew optional (leafless) dataclass fields
        since the checkpoint was written — ``TrainState.health`` is the
        canonical case — fails Orbax's structure match even though every
        *array* still lines up. On that specific key-mismatch the restore
        retries with the leafless fields pruned from the target
        (:func:`_shrink_empty_fields`) and grafts the arrays back into the
        caller's structure, so pre-upgrade runs keep resuming. A target
        whose new fields carry arrays (an armed guard against a pre-guard
        checkpoint) still fails loudly: restore unarmed, then arm.
        """
        manager = self._manager(identity)
        try:
            return manager.restore(epoch, args=ocp.args.StandardRestore(abstract))
        except ValueError as error:
            if 'key mismatch' not in str(error).lower():
                raise
            logger.warning(
                'restore target for %s/%d has fields the checkpoint '
                'predates; retrying with the legacy-shape subset (%s)',
                identity, epoch, str(error)[:200])
            image = manager.restore(
                epoch, args=ocp.args.StandardRestore(
                    _shrink_empty_fields(abstract)))
            return _graft_restored(abstract, image)

    def _restore_newest(self, identity: str, abstract: Any) -> tuple[Any, int]:
        """Restore the newest committed step, falling back over steps whose
        payload fails to load despite passing the probe (each discard
        logged); returns ``(state, step)``.

        If *every* committed step fails, the last underlying error is
        re-raised — a wrong restore target (model-config drift since the
        save) fails every step identically, and masking that as
        FileNotFoundError would let a create-or-resume caller silently
        reinitialize over good checkpoints.
        """
        candidates = self.committed(identity)
        errors: list[tuple[int, Exception]] = []
        for step in reversed(candidates):
            try:
                state = self._restore_step(identity, step, abstract)
                return state, step
            except Exception as error:  # torn payload that passed the probe
                errors.append((step, error))
                logger.warning(
                    'restore of %s/%s/%d failed (%s); falling back to the '
                    'previous committed step', self.root, identity, step, error)
        if errors:
            raise errors[-1][1]
        raise FileNotFoundError(
            f'no restorable checkpoint for identity {identity!r} under '
            f'{self.root}')

    def resume(self, identity: str, target: Any) -> tuple[Any, int, Any | None]:
        """One-call resume: ``(state, step, extras)`` from the newest
        committed checkpoint — the restart half of the preemption cycle.

        Uses the same newest-to-oldest fallback as the implicit
        :meth:`restore`: a step whose payload is torn despite a passing
        probe is logged and skipped, not crashed on. ``extras`` is whatever
        host metadata :meth:`save` stored (e.g. the data-loader cursor to
        :meth:`~tpusystem.data.Loader.seek`), or None.
        """
        state, step = self._restore_newest(identity, abstract_like(target))
        return state, step, self.extras(identity, step)

    def latest(self, identity: str) -> int | None:
        """Latest *committed* step for the identity, or ``None`` if fresh.

        Torn or corrupt step dirs (a kill mid-save, a truncated copy) are
        skipped with a logged warning — the create-or-resume decision
        (``.../services/compilation.py:41-57``) must land on a checkpoint
        that will actually restore. For allocating the *next* version
        number use :meth:`newest` — an async save still in flight has no
        committed dir yet and must not have its step reused.
        """
        steps = self.committed(identity)
        return steps[-1] if steps else None

    def newest(self, identity: str) -> int | None:
        """Newest *known* step — on disk (committed or torn) or still in
        flight as an async save. Version allocation only
        (``Repository.store``'s auto increment), never resume: a torn dir
        still owns its number (saving over it would collide) and an
        in-flight step has nothing readable on disk yet, so no integrity
        probe runs here. Like :meth:`save`, re-raises a background
        async-save failure instead of deferring it to ``wait``/``fence``."""
        self._surface_async_errors(identity)
        on_disk = self._disk_steps(identity)
        candidates = [step for step in (on_disk[-1] if on_disk else None,
                                        self._manager(identity).latest_step())
                      if step is not None]
        return max(candidates) if candidates else None

    def discard_after(self, identity: str, step: int) -> list[int]:
        """Drop every step dir newer than ``step`` — the rollback epilogue.

        After a sentinel rollback (:class:`tpusystem.train.Sentinel`), the
        steps beyond the rollback target are a dead branch: their params
        carry (or postdate) the anomaly, and leaving them on disk would
        make the retrained steps collide with their numbers
        (StepAlreadyExists) and make ``latest``/``resume`` prefer the bad
        branch after a crash. Waits out in-flight saves first, removes the
        dead steps (committed or torn) plus their sidecars, and lowers the
        commit fence to ``step`` if it pointed into the discarded range —
        the fence's "at least this step survived" promise transfers to the
        rollback target. Returns the discarded step numbers.
        """
        self.wait()
        dead = [at for at in self._disk_steps(identity) if at > step]
        manager = self._managers.get(identity)
        for at in dead:
            delete = getattr(manager, 'delete', None)
            try:
                if delete is not None:
                    delete(at)
                else:
                    shutil.rmtree(self.root / identity / str(at))
            except (OSError, ValueError):
                shutil.rmtree(self.root / identity / str(at),
                              ignore_errors=True)
            (self._extras_path(identity, at)).unlink(missing_ok=True)
            logger.warning('discarded dead-branch checkpoint %s/%s/%d '
                           '(rollback to %d)', self.root, identity, at, step)
        fenced = self.fenced(identity)
        if fenced is not None and fenced > step:
            _atomic_write(self.root / identity / _FENCE_FILE,
                          json.dumps({'step': int(step)}))
        return dead

    def epochs(self, identity: str) -> list[int]:
        """All retained committed epochs for the identity, ascending."""
        return self.committed(identity)

    def wait(self) -> None:
        """Block until every in-flight async save has committed."""
        for manager in self._managers.values():
            manager.wait_until_finished()

    def close(self) -> None:
        """Finalize pending saves and release resources."""
        for manager in self._managers.values():
            manager.wait_until_finished()
            manager.close()
        self._managers.clear()

    def __enter__(self) -> 'Checkpointer':
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
