"""Async sharded pytree checkpointer (Orbax-backed).

Replaces the reference's ``torch.save(model.nn, f'{root}/{id}.pth')`` +
``load_state_dict`` pair (``examples/tinysys/tinysys/repository.py:13-17``)
with a TPU-appropriate design:

* **sharded**: each host writes only the array shards it owns, so an 8B
  model on a v5p-64 checkpoints at aggregate disk bandwidth instead of
  funnelling through one host;
* **async**: the save is snapshotted and committed in the background, so the
  training loop resumes immediately (the analogue of keeping the bus off the
  hot path — SURVEY.md §7.3);
* **versioned by epoch**: one directory per identity, one step dir per epoch,
  enabling the reference's create-or-resume decision
  (``.../services/compilation.py:41-57``) via :meth:`Checkpointer.latest`.
"""

from __future__ import annotations

import pathlib
from typing import Any

import jax
import orbax.checkpoint as ocp


def abstract_like(tree: Any) -> Any:
    """Abstract pytree (shape/dtype/sharding) used as a restore target.

    Restoring onto the *current* mesh layout — not the layout at save time —
    is what makes checkpoints portable across topology changes (e.g. resume
    a v4-8 run on a v4-32).
    """
    def spec(leaf: Any) -> Any:
        if isinstance(leaf, jax.Array):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
        return leaf
    return jax.tree.map(spec, tree)


class Checkpointer:
    """Identity-keyed, epoch-versioned pytree store.

    Layout: ``{root}/{identity}/{epoch}/...`` — the identity is the registry
    hash of the aggregate (deterministic across hosts and restarts), so every
    worker independently computes the same directory and the restore decision
    needs no coordination.
    """

    def __init__(self, root: str | pathlib.Path, *, max_to_keep: int | None = 3,
                 keep_every: int | None = None,
                 async_save: bool = True) -> None:
        """``max_to_keep`` bounds the rolling window; ``keep_every`` pins
        every Nth epoch forever in addition (GC policy: a long run keeps
        recent checkpoints for resume plus periodic ones for analysis
        /rollback instead of losing all history to the window)."""
        self.root = pathlib.Path(root).absolute()
        self.max_to_keep = max_to_keep
        self.keep_every = keep_every
        self.async_save = async_save
        self._managers: dict[str, ocp.CheckpointManager] = {}

    def _manager(self, identity: str) -> ocp.CheckpointManager:
        if identity not in self._managers:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=self.max_to_keep,
                keep_period=self.keep_every,
                enable_async_checkpointing=self.async_save)
            self._managers[identity] = ocp.CheckpointManager(
                self.root / identity, options=options)
        return self._managers[identity]

    def save(self, identity: str, epoch: int, state: Any) -> None:
        """Snapshot ``state`` under (identity, epoch); returns immediately.

        With ``async_save`` the device buffers are copied out synchronously
        (cheap) and serialized in a background thread; call :meth:`wait` (or
        rely on save-on-next-epoch barriers) before reading the files.
        """
        self._manager(identity).save(epoch, args=ocp.args.StandardSave(state))

    def restore(self, identity: str, target: Any, epoch: int | None = None) -> Any:
        """Restore the pytree saved under (identity, epoch or latest).

        ``target`` may be a concrete pytree (its shapes/dtypes/shardings are
        used, see :func:`abstract_like`) or an abstract one. Each shard is
        read straight onto its mesh device.
        """
        manager = self._manager(identity)
        if epoch is None:
            epoch = manager.latest_step()
        if epoch is None:
            raise FileNotFoundError(f'no checkpoint for identity {identity!r} under {self.root}')
        abstract = abstract_like(target)
        return manager.restore(epoch, args=ocp.args.StandardRestore(abstract))

    def latest(self, identity: str) -> int | None:
        """Latest checkpointed epoch for the identity, or ``None`` if fresh.

        This is the TPU analogue of the reference's DB lookup deciding
        create-vs-resume (``.../services/compilation.py:41-57``).
        """
        return self._manager(identity).latest_step()

    def epochs(self, identity: str) -> list[int]:
        """All retained epochs for the identity, ascending."""
        return sorted(self._manager(identity).all_steps())

    def wait(self) -> None:
        """Block until every in-flight async save has committed."""
        for manager in self._managers.values():
            manager.wait_until_finished()

    def close(self) -> None:
        """Finalize pending saves and release resources."""
        for manager in self._managers.values():
            manager.wait_until_finished()
            manager.close()
        self._managers.clear()

    def __enter__(self) -> 'Checkpointer':
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
