"""Hot in-memory checkpoints: state that survives worker death.

Every recovery in PR 3–4 pays a full disk restore — correct, but the disk
round-trip dominates MTTR once the relaunch itself is seconds
(:mod:`tpusystem.parallel.supervisor`). Production systems keep *redundant
in-memory copies of the model state* outside the worker process (Gemini's
report; MegaScale's driver-side recovery) so a relaunched worker restores
from local RAM and a replaced host pulls a replica from a peer, with disk
as the verified fallback. This module is that tier:

* :func:`serialize_state` / :func:`deserialize_state` — a ``TrainState``
  pytree ⇄ one bytes blob of its host-side leaf arrays. The round trip is
  **bitwise exact** (``device_get`` → ``device_put`` onto the target's
  shardings), which is what lets :func:`hot_resume` promise restores
  identical to the disk path.
* :class:`MemStore` — the supervisor-side slot table: newest hot state per
  identity, every read digest-verified (a corrupted slot reads as absent,
  never as state). ``replica`` slots hold a *buddy host's* cross-replicated
  copy, served when a replaced host pulls over the control plane.
* :class:`MemStoreServer` / :class:`MemStoreClient` — the worker ⇄
  supervisor wire (chunked frames on a local TCP socket, address handed
  down via the ``TPUSYSTEM_SUPERVISOR`` env var). The client also carries
  ``mark()`` — the recovery-timeline breadcrumbs (``restore``,
  ``first-step``) the supervisor stamps into its
  :class:`~tpusystem.observe.events.RecoveryTimeline`.
* :func:`hot_resume` — the restart decision: prefer hot state only when
  its step is **at least** the newest committed disk step and its digest
  verifies; anything less (stale RAM, torn replica, no supervisor) falls
  back to :meth:`~tpusystem.checkpoint.Checkpointer.resume`.

The payload is host arrays only — like the control plane, never device
handles — so a blob is valid across processes and (for replicas) hosts.
On a multi-host pod each worker ships the shards *it* owns; the buddy pair
mirrors that host-local blob, so replication cost scales with the local
shard bytes, not the global model.
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any

from tpusystem.parallel.multihost import (BLOB_CHUNK, _blob_digest,
                                          _recv_frame, _send_frame)

logger = logging.getLogger('tpusystem.memstore')

__all__ = ['MemStore', 'MemStoreServer', 'MemStoreClient', 'HotState',
           'serialize_state', 'deserialize_state', 'hot_resume',
           'merge_hot', 'supervisor_client', 'SUPERVISOR_ENV']

# how a supervised worker finds its supervisor's memstore endpoint
SUPERVISOR_ENV = 'TPUSYSTEM_SUPERVISOR'


def blob_digest(data: bytes) -> str:
    """Integrity digest of a hot-state blob (BLAKE2b-128: fast, keyless —
    this detects corruption, it does not authenticate). The same
    primitive the transport's blob frames use, on purpose: the slot
    digest and the transfer digest must never diverge into two
    incompatible notions of "verified"."""
    return _blob_digest(data)


# ---------------------------------------------------------------------------
# state <-> bytes


def _index_key(index: tuple, shape: tuple) -> tuple:
    """Canonical hashable form of a shard's global-array slice tuple
    (``slice.indices`` normalizes the Nones a sharding API may emit)."""
    return tuple(part.indices(dim) for part, dim in zip(index, shape))


class ShardedLeaf:
    """Host-local shards of a cross-host-sharded array (picklable).

    On a multi-host pod a leaf sharded over hosts is not fully
    addressable — ``device_get`` on it would raise, and shipping the
    global array would defeat the point anyway. This carries only the
    shards *this host* holds, keyed by their global-array slice; the
    restore side reassembles them onto the target sharding's local
    devices (same host layout across a restart, the supervisor's case).
    """

    def __init__(self, shape: tuple, dtype: str, shards: dict) -> None:
        self.shape = shape
        self.dtype = dtype
        self.shards = shards       # {index key: np.ndarray (one per slice)}

    @classmethod
    def from_array(cls, leaf: Any) -> 'ShardedLeaf':
        import numpy as np
        shards: dict = {}
        for shard in leaf.addressable_shards:
            key = _index_key(shard.index, leaf.shape)
            if key not in shards:          # replicas hold identical bytes
                shards[key] = np.asarray(shard.data)
        return cls(tuple(leaf.shape), np.dtype(leaf.dtype).str, shards)

    def merged(self, other: 'ShardedLeaf') -> 'ShardedLeaf':
        """Union this host's pieces with another host's pieces of the SAME
        global array (the elastic-reshard assembly step: each survivor
        contributes its own shards, lost hosts' shards arrive via their
        buddies' replica blobs). Shape/dtype must agree; overlapping
        slices keep either copy (replicas hold identical bytes)."""
        import numpy as np
        if tuple(self.shape) != tuple(other.shape) or \
                np.dtype(self.dtype) != np.dtype(other.dtype):
            raise ValueError(
                f'cannot merge shards of different arrays: '
                f'{self.shape}/{self.dtype} vs {other.shape}/{other.dtype}')
        shards = dict(self.shards)
        shards.update(other.shards)
        return ShardedLeaf(self.shape, self.dtype, shards)

    def reassemble(self) -> Any:
        """The full global array from the held pieces, host-side — the
        re-layout path of an elastic resize, where the new mesh's slice
        boundaries need not line up with the old pieces. Raises
        ``ValueError`` when the pieces do not tile the whole array (a
        contributor's blob is missing; callers fall back to disk)."""
        import numpy as np
        full = np.empty(self.shape, np.dtype(self.dtype))
        covered = np.zeros(self.shape, bool)
        for key, data in self.shards.items():
            slices = tuple(slice(start, stop, step)
                           for start, stop, step in key)
            full[slices] = data
            covered[slices] = True
        if not covered.all():
            raise ValueError(
                f'hot shards cover only {int(covered.sum())} of '
                f'{covered.size} elements of a {self.shape} leaf — a '
                f'contributor\'s pieces are missing; restore from disk')
        return full

    def place(self, leaf: Any, reshard: bool = False) -> Any:
        """Reassemble onto ``leaf``'s sharding (raises ``ValueError`` when
        the target layout wants a slice this host never held — e.g. a
        resize between push and restore; callers fall back to disk).

        ``reshard=True`` is the elastic path: when the exact per-device
        slices do not line up (the mesh changed size), reassemble the
        full array from the pieces and re-lay it out onto the target
        sharding — still a ``ValueError`` when the pieces do not cover
        the array."""
        import jax
        import numpy as np
        if tuple(self.shape) != tuple(leaf.shape) or \
                np.dtype(self.dtype) != np.dtype(leaf.dtype):
            raise ValueError(
                f'hot-state leaf mismatch: blob has {self.shape}/'
                f'{self.dtype}, target wants {leaf.shape}/{leaf.dtype}')
        sharding = getattr(leaf, 'sharding', None)
        if sharding is None:
            raise ValueError('cannot place host-local shards without a '
                             'target sharding')
        index_map = sharding.addressable_devices_indices_map(
            tuple(self.shape))
        pieces = []
        for device, index in index_map.items():
            data = self.shards.get(_index_key(index, self.shape))
            if data is None:
                if reshard:
                    # new slice boundaries: rebuild the global array and
                    # let each (local) device take its slice of it —
                    # make_array_from_callback stays valid when the target
                    # sharding spans hosts (only local slices are read)
                    full = self.reassemble()
                    return jax.make_array_from_callback(
                        tuple(self.shape), sharding,
                        lambda index: full[index])
                raise ValueError(
                    'hot shards do not cover the restore layout (the mesh '
                    'changed since the push); restore from disk')
            pieces.append(jax.device_put(data, device))
        return jax.make_array_from_single_device_arrays(
            tuple(self.shape), sharding, pieces)


def serialize_state(state: Any) -> bytes:
    """One bytes blob of the pytree's leaf arrays, host-side.

    Fully-addressable leaves travel whole (``device_get`` materializes
    them exactly — no dtype or layout change), so
    :func:`deserialize_state` reproduces the state bitwise. A leaf
    sharded across hosts travels as its host-local shards only
    (:class:`ShardedLeaf`) — the blob scales with the local bytes, not
    the global model. Only leaves travel; the treedef is supplied by the
    restore target, the same contract as Orbax's ``StandardRestore``.
    """
    import jax
    import numpy as np
    leaves = []
    for leaf in jax.tree.leaves(state):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            leaves.append(ShardedLeaf.from_array(leaf))
        else:
            leaves.append(np.asarray(jax.device_get(leaf)))
    return pickle.dumps(leaves, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_state(blob: bytes, target: Any, *,
                      reshard: bool = False) -> Any:
    """Rebuild a pytree from :func:`serialize_state` bytes onto ``target``.

    ``target`` is a concrete or abstract pytree (see
    :func:`tpusystem.checkpoint.abstract_like`): each restored array is
    placed onto the corresponding leaf's sharding, so a hot restore lands
    exactly like a disk restore — current mesh, current layout. A
    structure, shape, or layout mismatch raises ``ValueError`` (the
    caller falls back to disk); it is never silently coerced.

    ``reshard=True`` is the elastic-resize path: sharded pieces whose old
    slice boundaries no longer line up with the target mesh are
    reassembled and re-laid-out (:meth:`ShardedLeaf.place`) instead of
    refused — shape/dtype/structure mismatches still raise.
    """
    import jax
    leaves, treedef = jax.tree.flatten(target)
    values = pickle.loads(blob)
    if len(values) != len(leaves):
        raise ValueError(
            f'hot state has {len(values)} leaves but the restore target '
            f'has {len(leaves)} — the run\'s state shape changed since the '
            f'blob was pushed')
    placed = []
    for value, leaf in zip(values, leaves):
        if isinstance(value, ShardedLeaf):
            placed.append(value.place(leaf, reshard=reshard))
            continue
        shape = getattr(leaf, 'shape', None)
        dtype = getattr(leaf, 'dtype', None)
        if shape is not None and (value.shape != shape
                                  or value.dtype != dtype):
            raise ValueError(
                f'hot-state leaf mismatch: blob has {value.shape}/'
                f'{value.dtype}, target wants {shape}/{dtype}')
        sharding = getattr(leaf, 'sharding', None)
        placed.append(jax.device_put(value, sharding)
                      if sharding is not None else jax.device_put(value))
    return jax.tree.unflatten(treedef, placed)


# ---------------------------------------------------------------------------
# the slot table


@dataclass
class HotState:
    """One identity's newest hot checkpoint."""

    step: int
    digest: str
    blob: bytes
    extras: Any | None = None
    source: str = 'local'     # 'local' (own worker) | 'replica' (buddy's)


def pack_hot(entry: HotState) -> bytes:
    """Wire form of a slot for cross-host replication (rides
    ``TcpTransport.send_blob``, which adds its own transfer digest)."""
    return pickle.dumps((entry.step, entry.digest, entry.extras, entry.blob),
                        protocol=pickle.HIGHEST_PROTOCOL)


def unpack_hot(data: bytes, source: str = 'replica') -> HotState:
    step, digest, extras, blob = pickle.loads(data)
    return HotState(step=int(step), digest=digest, blob=blob, extras=extras,
                    source=source)


def merge_hot(entries: list[HotState]) -> HotState:
    """Fold several hosts' hot blobs of the SAME step into one blob whose
    :class:`ShardedLeaf` leaves carry the union of every host's pieces —
    the assembly step of an elastic resize: each survivor contributes its
    own blob, lost hosts' blobs come from their buddies' replica slots.

    All entries must carry the same step (a mixed-step merge would stitch
    two different states together — refused with ``ValueError``; the
    caller falls back to disk). Fully-addressable leaves travel whole in
    every blob, so the first entry's copy is kept. ``extras`` come from
    the first entry (loader cursors are global, pushed identically by
    every host at the shared step cadence).
    """
    if not entries:
        raise ValueError('nothing to merge: no hot-state contributions')
    steps = {entry.step for entry in entries}
    if len(steps) > 1:
        raise ValueError(
            f'hot-state contributions disagree on the step ({sorted(steps)});'
            f' a mixed-step merge would stitch two states — restore from '
            f'disk')
    merged_leaves: list | None = None
    for entry in entries:
        leaves = pickle.loads(entry.blob)
        if merged_leaves is None:
            merged_leaves = list(leaves)
            continue
        if len(leaves) != len(merged_leaves):
            raise ValueError(
                f'hot-state contributions disagree on the leaf count '
                f'({len(merged_leaves)} vs {len(leaves)}); restore from disk')
        for index, leaf in enumerate(leaves):
            held = merged_leaves[index]
            if isinstance(held, ShardedLeaf) and isinstance(leaf, ShardedLeaf):
                merged_leaves[index] = held.merged(leaf)
    blob = pickle.dumps(merged_leaves, protocol=pickle.HIGHEST_PROTOCOL)
    first = entries[0]
    return HotState(step=first.step, digest=blob_digest(blob), blob=blob,
                    extras=first.extras, source='merged')


class MemStore:
    """Newest hot state per identity, digest-verified on every read.

    Two namespaces: the ``local`` slots hold what this host's own worker
    pushed; the ``replica`` slots hold a buddy host's cross-replicated
    copies, served when that host is replaced and its fresh supervisor
    pulls over the control plane. A slot whose bytes no longer match
    their digest — an SDC in RAM, a torn replication — reads as *absent*
    (logged), so corruption can only ever cost the hot tier, never
    deliver bad state.

    Also a valid in-process ``client`` for :func:`hot_resume` (it has the
    same ``fetch`` surface as :class:`MemStoreClient`), which is how the
    single-process drills and ``bench.py``'s ``recovery_seconds`` probe
    use it.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._slots: dict[tuple[str, bool], HotState] = {}

    def put(self, identity: str, step: int, blob: bytes, *,
            extras: Any | None = None, digest: str | None = None,
            replica: bool = False) -> HotState:
        """Install a slot (monotonic: an older step never replaces a newer
        one). A caller-supplied ``digest`` is verified before the bytes
        are accepted — a transfer torn upstream is rejected here too."""
        actual = blob_digest(blob)
        if digest is not None and digest != actual:
            raise ValueError(
                f'hot state for {identity!r} step {step} failed its digest '
                f'check on arrival; rejected')
        entry = HotState(step=int(step), digest=actual, blob=bytes(blob),
                         extras=extras,
                         source='replica' if replica else 'local')
        with self._lock:
            held = self._slots.get((identity, replica))
            if held is not None and held.step > entry.step:
                return held
            self._slots[(identity, replica)] = entry
        return entry

    def newest(self, identity: str, *, replica: bool = False) -> HotState | None:
        """The identity's slot, or None — also when the held bytes fail
        their digest (the slot is dropped and logged: corrupt hot state
        must read as absent, never restore)."""
        with self._lock:
            entry = self._slots.get((identity, replica))
        if entry is None:
            return None
        if blob_digest(entry.blob) != entry.digest:
            logger.warning(
                'hot state for %r step %d failed its digest check in the '
                'store; dropping the slot (disk is the fallback)',
                identity, entry.step)
            with self._lock:
                if self._slots.get((identity, replica)) is entry:
                    del self._slots[(identity, replica)]
            return None
        return entry

    # the MemStoreClient-compatible read surface (in-process client)
    def fetch(self, identity: str) -> HotState | None:
        return self.newest(identity)

    def drop(self, identity: str, *, replica: bool = False) -> None:
        with self._lock:
            self._slots.pop((identity, replica), None)

    def identities(self) -> list[str]:
        with self._lock:
            return sorted({name for name, _ in self._slots})


# ---------------------------------------------------------------------------
# worker <-> supervisor wire
#
# Frames (length-prefixed pickles, the control plane's framing) on a local
# TCP socket; only the worker initiates, so replies cannot interleave:
#   ('put', identity, step, digest, extras, total) + total x ('chunk', i, b)
#       -> ('ok', step) | ('bad', message)
#   ('get', identity)
#       -> ('hot', step, digest, extras, total) + chunks | ('none',)
#   ('mark', stage, info)            fire-and-forget timeline breadcrumb


class MemStoreServer:
    """The supervisor's memstore endpoint (one thread per connection).

    Hooks: ``on_put(identity, entry)`` fires after a verified local push
    (the supervisor's replication rider); ``on_mark(stage, info)`` carries
    the worker's timeline breadcrumbs; ``fetch_fallback(identity)`` is
    consulted when a ``get`` misses locally (the supervisor's
    pull-from-buddy path).
    """

    def __init__(self, store: MemStore | None = None,
                 host: str = '127.0.0.1', port: int = 0,
                 on_put: Any = None, on_mark: Any = None,
                 fetch_fallback: Any = None,
                 chunk_size: int = BLOB_CHUNK) -> None:
        self.store = store if store is not None else MemStore()
        self.on_put = on_put
        self.on_mark = on_mark
        self.fetch_fallback = fetch_fallback
        self.chunk_size = chunk_size
        self._server = socket.create_server((host, port))
        self.address = self._server.getsockname()
        self._closed = threading.Event()
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._accept = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept.start()

    @property
    def env(self) -> dict[str, str]:
        """The environment entry a spawned worker needs to find us."""
        return {SUPERVISOR_ENV: f'{self.address[0]}:{self.address[1]}'}

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.append(sock)
            threading.Thread(target=self._serve, args=(sock,),
                             daemon=True).start()

    def _serve(self, sock: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                frame = _recv_frame(sock)
                if frame is None:
                    return
                kind = frame[0]
                if kind == 'put':
                    self._handle_put(sock, frame)
                elif kind == 'get':
                    self._handle_get(sock, frame[1])
                elif kind == 'mark':
                    if self.on_mark is not None:
                        self.on_mark(frame[1], frame[2])
        except OSError:
            pass
        finally:
            sock.close()

    def _handle_put(self, sock: socket.socket, frame: tuple) -> None:
        _, identity, step, digest, extras, total = frame
        parts: list[bytes] = []
        for _ in range(total):
            chunk = _recv_frame(sock)
            if chunk is None or chunk[0] != 'chunk':
                raise OSError('put stream ended mid-transfer')
            parts.append(chunk[2])
        blob = b''.join(parts)
        try:
            entry = self.store.put(identity, step, blob, extras=extras,
                                   digest=digest)
        except ValueError as error:
            logger.warning('rejected hot push for %r step %d: %s',
                           identity, step, error)
            _send_frame(sock, ('bad', str(error)))
            return
        _send_frame(sock, ('ok', entry.step))
        if self.on_put is not None and entry.step == int(step):
            self.on_put(identity, entry)

    def _handle_get(self, sock: socket.socket, identity: str) -> None:
        entry = self.store.newest(identity)
        if entry is None and self.fetch_fallback is not None:
            try:
                entry = self.fetch_fallback(identity)
            except Exception as error:
                logger.warning('hot-state fallback fetch for %r failed: %s',
                               identity, error)
                entry = None
        if entry is None:
            _send_frame(sock, ('none',))
            return
        _send_frame(sock, ('hot', entry.step, entry.digest, entry.extras,
                           max(1, -(-len(entry.blob) // self.chunk_size))))
        for index in range(0, len(entry.blob) or 1, self.chunk_size):
            _send_frame(sock, ('chunk', index // self.chunk_size,
                               entry.blob[index:index + self.chunk_size]))

    def close(self) -> None:
        self._closed.set()
        # shutdown before close on the LISTENER too: a close() alone
        # does not unblock the accept thread on Linux, which then holds
        # the kernel's reference to the listening fd forever — the port
        # stays bound and a restarted supervisor cannot re-listen at
        # its own address (found by the bounced-server redial drill)
        try:
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._server.close()
        self._accept.join(timeout=5.0)
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for sock in conns:
            # shutdown before close (the Hub teardown discipline): a serve
            # thread blocked in recv on the same fd would otherwise hold
            # the connection open, and clients of a dead supervisor must
            # see the death immediately, not at their next recv
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()


class MemStoreClient:
    """The worker's handle on its supervisor's memstore.

    Every method degrades instead of raising on a dead or wedged
    supervisor socket: hot state is an accelerator, never a requirement,
    and a hot-tier-only failure must not take down training that disk
    checkpoints would have carried (``push`` returns False, ``fetch``
    returns None — both logged once).

    A dead socket is not forever: a supervisor that RESTARTS listens at
    the same address again, and pushes that stopped flowing would leave
    journal/hot-state durability silently frozen for the rest of the
    run. So on failure the client drops the socket and **redials** on
    the next call — bounded (``redials`` attempts per outage, a fresh
    budget after any success) and backed off (``redial_backoff * 2 **
    attempt`` capped at ``redial_cap``; calls inside the backoff window
    just degrade, they never sleep — the caller is the serving/training
    hot loop). Budget exhausted = the old permanent degradation, logged
    once."""

    def __init__(self, address: tuple[str, int],
                 chunk_size: int = BLOB_CHUNK, *, redials: int = 8,
                 redial_backoff: float = 0.5, redial_cap: float = 30.0,
                 clock: Any = None) -> None:
        self.address = tuple(address)
        self.chunk_size = chunk_size
        self.redials = redials
        self.redial_backoff = redial_backoff
        self.redial_cap = redial_cap
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._down = False
        self._attempts = 0           # redials consumed this outage
        self._retry_at = 0.0         # earliest next redial (clock units)
        self._sock: socket.socket | None = socket.create_connection(
            self.address, timeout=10.0)
        self._sock.settimeout(None)

    def _lost(self, what: str, error: Any) -> None:
        """Drop the dead socket and arm the redial backoff. Called with
        ``_lock`` held (every wire method owns the lock around its whole
        exchange)."""
        if not self._down:      # log the first failure, not every step
            logger.warning('supervisor unreachable during %s (%s); hot '
                           'state degraded — disk checkpoints still stand, '
                           'redialing with backoff (%d attempts left)',
                           what, error, max(0, self.redials - self._attempts))
        self._down = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        backoff = min(self.redial_cap,
                      self.redial_backoff * 2 ** self._attempts)
        self._retry_at = self._clock() + backoff

    def _ensure(self) -> bool:
        """True when a live socket is available — redialing a restarted
        supervisor when the backoff window has passed and the outage
        budget allows. Called with ``_lock`` held."""
        if self._sock is not None:
            return True
        if self._attempts >= self.redials:
            return False             # budget spent: permanently degraded
        if self._clock() < self._retry_at:
            return False             # inside the backoff window: degrade
        self._attempts += 1
        try:
            sock = socket.create_connection(self.address, timeout=10.0)
        except OSError as error:
            backoff = min(self.redial_cap,
                          self.redial_backoff * 2 ** self._attempts)
            self._retry_at = self._clock() + backoff
            if self._attempts >= self.redials:
                logger.warning(
                    'supervisor at %r still unreachable after %d redials '
                    '(%s); hot state disabled for the rest of this run',
                    self.address, self._attempts, error)
            return False
        sock.settimeout(None)
        self._sock = sock
        logger.info('supervisor at %r reachable again after %d redial(s); '
                    'hot-state pushes resume', self.address, self._attempts)
        return True

    def push(self, identity: str, step: int, state: Any, *,
             extras: Any | None = None) -> bool:
        """Ship the state's hot blob to the supervisor. True means the
        supervisor holds a digest-verified copy (synchronous ack — the
        hot tier's analogue of the disk fence); False means the
        supervisor is gone and only disk protects this step."""
        blob = state if isinstance(state, bytes) else serialize_state(state)
        digest = blob_digest(blob)
        total = max(1, -(-len(blob) // self.chunk_size))
        with self._lock:
            if not self._ensure():
                return False
            sock = self._sock        # close() may null the attr mid-call;
            try:                     # the local keeps failures typed OSError
                _send_frame(sock, ('put', identity, int(step), digest,
                                   extras, total))
                for index in range(total):
                    _send_frame(
                        sock,
                        ('chunk', index,
                         blob[index * self.chunk_size:
                              (index + 1) * self.chunk_size]))
                reply = _recv_frame(sock)
            except OSError as error:
                self._lost(f'push of {identity!r} step {step}', error)
                return False
            if reply is None:
                self._lost(f'push of {identity!r} step {step}',
                           'connection closed')
                return False
            if reply[0] != 'ok':     # the store REFUSED (e.g. digest):
                # the socket is healthy — a rejection is not an outage
                logger.warning('hot push of %r step %d rejected: %s',
                               identity, step, reply[1])
                return False
            self._down = False
            self._attempts = 0       # a success refills the redial budget
        return True

    def fetch(self, identity: str) -> HotState | None:
        """The supervisor's newest hot state for the identity, or None
        (missing, digest failed, or the supervisor is unreachable —
        either way: fall back to disk)."""
        with self._lock:
            if not self._ensure():
                return None
            sock = self._sock
            try:
                _send_frame(sock, ('get', identity))
                reply = _recv_frame(sock)
                if reply is None:
                    self._lost(f'fetch of {identity!r}',
                               'connection closed')
                    return None
                if reply[0] == 'none':
                    self._down = False
                    self._attempts = 0
                    return None
                _, step, digest, extras, total = reply
                parts = []
                for _ in range(total):
                    chunk = _recv_frame(sock)
                    if chunk is None:
                        self._lost(f'fetch of {identity!r}',
                                   'stream ended mid-transfer')
                        return None
                    parts.append(chunk[2])
            except OSError as error:
                self._lost(f'fetch of {identity!r}', error)
                return None
            self._down = False
            self._attempts = 0
        blob = b''.join(parts)
        if blob_digest(blob) != digest:
            logger.warning('fetched hot state for %r step %d failed its '
                           'digest check; treating as absent', identity, step)
            return None
        return HotState(step=int(step), digest=digest, blob=blob,
                        extras=extras)

    def mark(self, stage: str, **info: Any) -> None:
        """Timeline breadcrumb (``restore``, ``first-step``, ``fence``):
        fire-and-forget; the supervisor stamps arrival time and folds it
        into the :class:`~tpusystem.observe.events.RecoveryTimeline`."""
        with self._lock:
            if not self._ensure():
                return
            try:
                _send_frame(self._sock, ('mark', stage, dict(info)))
            except (OSError, AttributeError) as error:
                # a dying supervisor must not take the worker with it
                # (AttributeError: close() nulled the socket mid-call)
                self._lost(f'mark {stage!r}', error)

    def close(self) -> None:
        # deliberately lock-free: a wire call blocked in recv on a hung
        # supervisor socket HOLDS the lock — close() must be able to
        # force the socket shut underneath it (the blocked call then
        # surfaces OSError and degrades). Spending the redial budget
        # first keeps a racing _ensure from dialing a fresh socket.
        self._attempts = self.redials       # closed on purpose: no redial
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def supervisor_client(env: dict | None = None) -> MemStoreClient | None:
    """The worker-side entry: connect to the supervisor named by
    ``TPUSYSTEM_SUPERVISOR`` (host:port), or None when unsupervised /
    unreachable — hot state is an accelerator, never a requirement, so a
    worker that cannot reach its supervisor trains on (disk still
    checkpoints) instead of refusing to start."""
    spec = (env if env is not None else os.environ).get(SUPERVISOR_ENV)
    if not spec:
        return None
    host, _, port = spec.rpartition(':')
    try:
        return MemStoreClient((host, int(port)))
    except (OSError, ValueError) as error:
        logger.warning('supervisor at %r unreachable (%s); hot state '
                       'disabled for this run', spec, error)
        return None


# ---------------------------------------------------------------------------
# the restart decision


def hot_resume(checkpointer: Any, identity: str, target: Any,
               client: Any = None) -> tuple[Any, int, Any | None, str]:
    """Resume preferring hot state over disk: ``(state, step, extras,
    source)`` with ``source`` in ``{'hot', 'disk'}``.

    The preference is deliberately conservative — RAM wins only when it
    cannot lose information or integrity:

    * the hot step must be **>= the newest committed disk step** (a stale
      slot — e.g. pushes stopped while disk saves continued — must not
      silently rewind training);
    * the blob's digest must verify (enforced by every fetch surface) and
      its leaves must match the target's structure/shapes — any mismatch
      logs and falls back.

    Both paths materialize the same bytes onto the same shardings, so a
    hot restore is bitwise-identical to restoring the disk checkpoint of
    the same step (asserted in ``tests/test_supervisor.py``). When
    ``client`` carries a ``mark`` method the decision is stamped into the
    recovery timeline as the ``restore`` breadcrumb.
    """
    from tpusystem.checkpoint.checkpointer import abstract_like
    hot = client.fetch(identity) if client is not None else None
    disk_step = None
    if hot is not None:
        disk_step = checkpointer.latest(identity)
        if disk_step is not None and hot.step < disk_step:
            logger.warning(
                'hot state for %r is stale (step %d < committed disk step '
                '%d); restoring from disk', identity, hot.step, disk_step)
            hot = None
    result = None
    if hot is not None:
        try:
            state = deserialize_state(hot.blob, abstract_like(target))
            result = (state, hot.step, hot.extras, 'hot')
        except (ValueError, pickle.UnpicklingError) as error:
            logger.warning('hot state for %r step %d failed to restore '
                           '(%s); falling back to disk', identity, hot.step,
                           error)
    if result is None:
        state, step, extras = checkpointer.resume(identity, target)
        result = (state, step, extras, 'disk')
    mark = getattr(client, 'mark', None)
    if mark is not None:
        mark('restore', source=result[3], step=result[1])
    return result
