"""Weight repository keyed by aggregate identity.

Reference-parity surface (``examples/tinysys/tinysys/repository.py``): the
repository stores and restores an aggregate's learned state, addressed purely
by ``aggregate.id`` — same hyperparameters, same identity, same checkpoint,
across process restarts and host counts. Here the stored payload is the
aggregate's device-state pytree (``aggregate.state``, a
:class:`tpusystem.train.TrainState` or any pytree) rather than a pickled
module, and saves are async + sharded via :class:`Checkpointer`.
"""

from __future__ import annotations

import pathlib
from typing import Any, Protocol, runtime_checkable

from tpusystem.checkpoint.checkpointer import Checkpointer


@runtime_checkable
class Stateful(Protocol):
    """Anything with an identity and a device-state pytree attribute."""
    id: Any
    state: Any


class Repository:
    """Store/restore aggregates by identity hash.

    ``epoch`` defaults to the aggregate's own ``epoch`` attribute when it has
    one (the reference saves every epoch via the ``Iterated`` event —
    ``.../services/storage.py:84-86``), else to the next free version.
    """

    def __init__(self, root: str | pathlib.Path = 'data/weights', *,
                 max_to_keep: int | None = 3, async_save: bool = True) -> None:
        self.checkpointer = Checkpointer(root, max_to_keep=max_to_keep,
                                         async_save=async_save)

    def store(self, aggregate: Any, epoch: int | None = None, *,
              extras: Any | None = None) -> None:
        """Persist ``aggregate.state`` under its identity.

        ``extras`` is optional JSON-able host metadata (e.g. a data-loader
        cursor for step-granular resume) stored alongside the pytree."""
        if epoch is None:
            epoch = getattr(aggregate, 'epoch', None)
        if epoch is None:
            # newest(), not latest(): an async save still in flight owns
            # its step number even though nothing committed yet
            newest = self.checkpointer.newest(str(aggregate.id))
            epoch = 0 if newest is None else newest + 1
        self.checkpointer.save(str(aggregate.id), epoch, aggregate.state,
                               extras=extras)

    def restore(self, aggregate: Any, epoch: int | None = None) -> None:
        """Load the stored pytree back into ``aggregate.state`` in place.

        The current state's shapes/dtypes/shardings are the restore target,
        so the weights land sharded for the *current* mesh even when saved on
        a different topology.
        """
        aggregate.state = self.checkpointer.restore(
            str(aggregate.id), aggregate.state, epoch)

    def latest(self, aggregate: Any) -> int | None:
        """Latest stored epoch for this aggregate, or ``None`` if fresh."""
        return self.checkpointer.latest(str(aggregate.id))

    def resume(self, aggregate: Any) -> tuple[int, Any | None]:
        """Restore the newest committed checkpoint into ``aggregate.state``
        and return ``(step, extras)`` — the restart half of preemption
        recovery (extras carries e.g. the loader cursor)."""
        state, step, extras = self.checkpointer.resume(
            str(aggregate.id), aggregate.state)
        aggregate.state = state
        return step, extras

    def fence(self, aggregate: Any) -> int | None:
        """Block until pending saves commit, then advance the monotonic
        commit fence for this aggregate — the emergency-checkpoint
        durability receipt (see :meth:`Checkpointer.fence`)."""
        return self.checkpointer.fence(str(aggregate.id))

    def wait(self) -> None:
        self.checkpointer.wait()

    def close(self) -> None:
        self.checkpointer.close()
