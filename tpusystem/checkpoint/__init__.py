"""Checkpoint/resume subsystem.

The reference persists weights with ``torch.save`` into a file named by the
model's registry hash (``examples/tinysys/tinysys/repository.py:13-17``) and
resumes by looking that id up again (``.../services/compilation.py:41-64``).
The TPU-native equivalent keeps the same *flow* — identity hash names the
checkpoint location, the build pipeline decides create/resume — but the
mechanism is an async, sharded pytree checkpointer: every host writes only
its own shards, saves overlap the next training step, and restore places
each shard directly onto its mesh position.
"""

from tpusystem.checkpoint.checkpointer import Checkpointer, abstract_like
from tpusystem.checkpoint.memstore import (HotState, MemStore, MemStoreClient,
                                           MemStoreServer, deserialize_state,
                                           hot_resume, merge_hot,
                                           serialize_state, supervisor_client)
from tpusystem.checkpoint.repository import Repository

__all__ = ['Checkpointer', 'Repository', 'abstract_like',
           'MemStore', 'MemStoreServer', 'MemStoreClient', 'HotState',
           'serialize_state', 'deserialize_state', 'hot_resume', 'merge_hot',
           'supervisor_client']
