"""Constructor-argument capture for entity identity.

Registering a class wraps its ``__init__`` so every instance records the
arguments it was constructed with. Unlike the reference implementation
(``torchsystem/registry/core.py:42-59``), captured metadata lives in a
*side table* keyed by object identity instead of instance attributes — this
makes capture work for frozen dataclasses (flax ``linen.Module``), slotted
classes, and other immutable pytree nodes that reject ``setattr``.

Entries are garbage-collected with the instance via ``weakref.finalize``
where the type supports weak references; otherwise they persist for the
process lifetime (equivalent to the reference's instance-attribute storage).
"""

from __future__ import annotations

import weakref
from copy import deepcopy
from inspect import signature
from typing import Any

# id(obj) -> captured metadata. Three parallel tables so hash/name overrides
# can exist without captured arguments and vice versa.
_ARGUMENTS: dict[int, dict[str, Any]] = {}
_NAMES: dict[int, str] = {}
_HASHES: dict[int, str] = {}


def _attach_finalizer(obj: object) -> None:
    key = id(obj)

    def _cleanup(key=key):
        _ARGUMENTS.pop(key, None)
        _NAMES.pop(key, None)
        _HASHES.pop(key, None)

    try:
        weakref.finalize(obj, _cleanup)
    except TypeError:
        pass  # not weakref-able: entry lives as long as the process


def put_arguments(obj: object, arguments: dict[str, Any]) -> None:
    _attach_finalizer(obj)
    _ARGUMENTS[id(obj)] = arguments


def get_arguments(obj: object) -> dict[str, Any] | None:
    return _ARGUMENTS.get(id(obj))


def put_name(obj: object, name: str) -> None:
    _attach_finalizer(obj)
    _NAMES[id(obj)] = name


def get_name(obj: object) -> str | None:
    return _NAMES.get(id(obj))


def put_hash(obj: object, value: str) -> None:
    _attach_finalizer(obj)
    _HASHES[id(obj)] = value


def get_hash(obj: object) -> str | None:
    return _HASHES.get(id(obj))


def has_capture(obj: object) -> bool:
    return id(obj) in _ARGUMENTS or id(obj) in _HASHES


def cls_signature(cls: type,
                  excluded_args: list[int] | None = None,
                  excluded_kwargs: set[str] | None = None) -> dict[str, str]:
    """Map constructor parameter names to annotation type-names.

    Positional indices in ``excluded_args`` and names in ``excluded_kwargs``
    are omitted — used e.g. to exclude a parameter pytree from an optimizer's
    identity (reference parity: ``torchsystem/registry/core.py:5-12``).
    """
    excluded_args = excluded_args or []
    excluded_kwargs = excluded_kwargs or set()
    result: dict[str, str] = {}
    for index, (key, value) in enumerate(signature(cls).parameters.items()):
        if index in excluded_args or key in excluded_kwargs:
            continue
        if value.annotation is value.empty:
            result[key] = 'Any'
        else:
            result[key] = getattr(value.annotation, '__name__', str(value.annotation))
    return result


def describe_value(value: Any) -> Any:
    """Serialize one constructor argument for identity purposes.

    A registered argument collapses recursively to
    ``{'name': ..., 'arguments': ...}`` — or to its bare name when it captured
    no arguments (reference contract ``torchsystem/registry/core.py:15-26``,
    pinned by ``tests/registry/test_nest.py:26-35``).
    """
    captured = get_arguments(value) if not isinstance(value, (int, float, str, bool, type(None))) else None
    if captured is not None:
        name = get_name(value) or value.__class__.__name__
        if captured:
            return deepcopy({'name': name, 'arguments': captured})
        return name
    return value


def _safe_deepcopy(value: Any) -> Any:
    try:
        return deepcopy(value)
    except Exception:
        return value


def parse_call(args: tuple, kwargs: dict[str, Any],
               parameter_names: list[str],
               excluded_args: list[int],
               excluded_kwargs: set[str]) -> dict[str, Any]:
    """Capture a call's arguments by name, honoring positional/keyword
    exclusions. Positional args align with the *full* parameter list and are
    filtered by index afterwards (reference parity:
    ``torchsystem/registry/core.py:28-40``)."""
    captured: dict[str, Any] = {}
    for index, (arg, key) in enumerate(zip(args, parameter_names)):
        if index not in excluded_args:
            captured[key] = describe_value(arg)
    for key, arg in kwargs.items():
        if key not in excluded_kwargs:
            captured[key] = describe_value(arg)
    return _safe_deepcopy(captured)


def override_init(cls: type,
                  excluded_args: list[int] | None = None,
                  excluded_kwargs: set[str] | None = None,
                  name: str | None = None) -> type:
    """Wrap ``cls.__init__`` to capture construction arguments per instance."""
    original = cls.__init__
    parameter_names = list(signature(cls).parameters.keys())
    excluded_args = excluded_args or []
    excluded_kwargs = excluded_kwargs or set()

    def init_wrapper(obj, *args, **kwargs):
        original(obj, *args, **kwargs)
        put_arguments(obj, parse_call(args, kwargs, parameter_names, excluded_args, excluded_kwargs))
        if name:
            put_name(obj, name)

    init_wrapper.__wrapped__ = original
    cls.__init__ = init_wrapper
    return cls
