from tpusystem.registry.accessors import (
    Registry,
    getarguments,
    gethash,
    getmetadata,
    getname,
    register,
    sethash,
    setname,
)

__all__ = ['Registry', 'register', 'getarguments', 'getname', 'gethash',
           'sethash', 'setname', 'getmetadata']
