"""Entity identity accessors.

Models, optimizers, loaders etc. become *entities* once registered: their
constructor arguments are captured and a deterministic hash derived from
``name + arguments`` identifies them across processes, hosts and restarts.
That hash keys experiment rows and checkpoint directories, enabling
transparent resume (reference flow ``torchsystem/registry/accessors.py:45-68``
-> ``examples/tinysys/tinysys/services/compilation.py:41-64``).

Determinism matters doubly on a TPU pod: every worker must compute the same
id so all hosts agree on which checkpoint to restore. The hash algorithm is
``md5(name + json.dumps(arguments))`` — identical to the reference so
identities are stable and portable.
"""

from __future__ import annotations

from hashlib import md5
from json import dumps
from typing import Any, Callable, Optional, TypeVar, overload

from tpusystem.registry import core

T = TypeVar('T')


def getarguments(obj: object) -> dict[str, Any]:
    """Captured constructor arguments of a registered object.

    Raises:
        AttributeError: if the object's class was never registered
            (reference parity ``torchsystem/registry/accessors.py:11-27``).
    """
    arguments = core.get_arguments(obj)
    if arguments is None:
        raise AttributeError(
            f'{obj.__class__.__name__} is not registered: no captured arguments')
    return arguments


def getname(obj: object) -> str:
    """Registered alias of the object, falling back to its class name."""
    return core.get_name(obj) or obj.__class__.__name__


def _hash_fallback(value: Any) -> Any:
    """Deterministic JSON encoding for non-JSON captured arguments.

    Types (including dtype sentinels like ``jnp.bfloat16``) encode as their
    name; sets encode as sorted lists (set repr order is hash-randomized
    across processes). A value whose repr embeds a memory address has no
    stable cross-process identity — raising here (like the reference's bare
    ``json.dumps`` would) beats silently aliasing two different experiments
    to one hash; the fix is to ``register`` the value's class.
    """
    if isinstance(value, type):
        return getattr(value, '__name__', str(value))
    if isinstance(value, (set, frozenset)):
        return sorted(dumps(item, default=_hash_fallback) for item in value)
    rendered = repr(value)
    if ' at 0x' in rendered:
        raise TypeError(
            f'cannot derive a stable identity for captured argument of type '
            f'{value.__class__.__qualname__}: its repr embeds a memory address. '
            f'register() its class so it captures constructor arguments, or '
            f'exclude it via excluded_args/excluded_kwargs.')
    return rendered


def gethash(obj: object) -> str:
    """Deterministic identity hash of a registered object.

    A manually assigned hash (:func:`sethash`) takes precedence; otherwise
    ``md5(getname(obj) + json.dumps(getarguments(obj)))``. Non-JSON argument
    values (dtypes, nested unregistered objects) are canonicalized via
    :func:`_hash_fallback`; pure-JSON captures hash byte-identically to the
    reference (pinned digest ``b12461be...``).

    Raises:
        AttributeError: when the object has neither captured arguments nor a
            manual hash.
    """
    manual = core.get_hash(obj)
    if manual is not None:
        return manual
    if core.get_arguments(obj) is None:
        raise AttributeError(
            f'{obj.__class__.__name__} has no identity: register the class or sethash()')
    payload = dumps(getarguments(obj), default=_hash_fallback)
    return md5((getname(obj) + payload).encode()).hexdigest()


def sethash(obj: object, hash: str | None = None) -> None:
    """Assign an identity hash manually; ``None`` freezes the computed one."""
    core.put_hash(obj, hash if hash is not None else gethash(obj))


def setname(obj: object, name: str | None = None) -> None:
    """Assign a name alias manually; ``None`` freezes the current name."""
    core.put_name(obj, name if name is not None else getname(obj))


def getmetadata(obj: object) -> dict[str, Any]:
    """All identity metadata present on the object: hash?, name?, arguments?"""
    metadata: dict[str, Any] = {}
    if (manual := core.get_hash(obj)) is not None:
        metadata['hash'] = manual
    if (alias := core.get_name(obj)) is not None:
        metadata['name'] = alias
    if (arguments := core.get_arguments(obj)) is not None:
        metadata['arguments'] = arguments
    return metadata


@overload
def register(cls: type, excluded_args: list[int] | None = None,
             excluded_kwargs: set[str] | None = None) -> type: ...


@overload
def register(cls: str, excluded_args: list[int] | None = None,
             excluded_kwargs: set[str] | None = None) -> Callable[[type], type]: ...


def register(cls: type | str | None = None,
             excluded_args: list[int] | None = None,
             excluded_kwargs: set[str] | None = None):
    """Register a class for argument capture.

    Usable three ways (reference parity
    ``torchsystem/registry/accessors.py:119-193``)::

        register(MLP)                      # plain call
        @register                          # bare decorator
        class Model: ...
        @register('Criterion')             # rename decorator
        class CrossEntropy: ...
        register(Adam, excluded_args=[0])  # exclude the params arg from identity
    """
    if isinstance(cls, type):
        return core.override_init(cls, excluded_args, excluded_kwargs)
    name = cls

    def wrapper(klass: type) -> type:
        return core.override_init(klass, excluded_args, excluded_kwargs, name)
    return wrapper


class Registry:
    """Name-indexed catalog of registered types.

    Enables dynamic construction from configuration files or remote commands:
    resolve a name to a class, inspect its signature, build it — and the
    instance carries its identity hash automatically
    (reference parity ``torchsystem/registry/accessors.py:233-312``).
    """

    def __init__(self) -> None:
        self.types: dict[str, type] = {}
        self.signatures: dict[str, dict[str, str]] = {}

    @overload
    def register(self, cls: str, excluded_args: list[int] | None = None,
                 excluded_kwargs: set[str] | None = None) -> Callable[[type], type]: ...

    @overload
    def register(self, cls: type, excluded_args: list[int] | None = None,
                 excluded_kwargs: set[str] | None = None) -> type: ...

    def register(self, cls, excluded_args=None, excluded_kwargs=None):
        if isinstance(cls, type):
            self.types[cls.__name__] = cls
            self.signatures[cls.__name__] = core.cls_signature(cls, excluded_args, excluded_kwargs)
            return core.override_init(cls, excluded_args, excluded_kwargs)
        name = cls

        def wrapper(klass: type) -> type:
            self.types[name] = klass
            self.signatures[name] = core.cls_signature(klass, excluded_args, excluded_kwargs)
            return core.override_init(klass, excluded_args, excluded_kwargs, name)
        return wrapper

    def get(self, name: str) -> Optional[type]:
        return self.types.get(name)

    def keys(self) -> list[str]:
        return list(self.types.keys())

    def signature(self, name: str) -> Optional[dict[str, str]]:
        return self.signatures.get(name)
