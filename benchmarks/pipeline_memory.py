"""Compiled peak temp memory: GPipe-autodiff vs the 1F1B schedule
(virtual 4-stage CPU mesh, 16 microbatches) — BASELINE.md round-2 numbers.
"""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
import jax, jax.numpy as jnp, numpy as np
from tpusystem.parallel import force_host_platform
force_host_platform(4)
from tpusystem.models import GPT2Pipelined
from tpusystem.parallel import MeshSpec
from tpusystem.train import (NextTokenLoss, SGD, build_1f1b_train_step,
                             build_train_step, flax_apply, init_state)

M = 16
mesh = MeshSpec(stage=4).build()
model = GPT2Pipelined(vocab_size=256, layers=4, dim=256, heads=4,
                      max_seq=512, dtype='float32', microbatches=M, mesh=mesh)
tokens = jnp.zeros((M, 512), jnp.int32)
state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)

def report(tag, step_fn):
    lowered = jax.jit(step_fn, donate_argnums=0).lower(state, tokens, tokens)
    mem = lowered.compile().memory_analysis()
    print(tag, 'temp MB:', round(mem.temp_size_in_bytes / 2**20, 1),
          'total MB:', round((mem.temp_size_in_bytes + mem.argument_size_in_bytes) / 2**20, 1))

report('gpipe+autodiff', build_train_step(flax_apply(model), NextTokenLoss(), SGD(lr=0.1), jit=False))
report('1f1b          ', build_1f1b_train_step(model, NextTokenLoss(), SGD(lr=0.1), jit=False))
