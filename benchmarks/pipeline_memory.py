"""Compiled peak temp memory + schedule accounting: GPipe-autodiff vs 1F1B
vs interleaved 1F1B (virtual 4-stage CPU mesh) — BASELINE.md numbers.
"""
import sys, pathlib; sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
import jax, jax.numpy as jnp, numpy as np
from tpusystem.parallel import force_host_platform
force_host_platform(4)
from tpusystem.models import GPT2Pipelined
from tpusystem.parallel import MeshSpec
from tpusystem.train import (NextTokenLoss, SGD, build_1f1b_train_step,
                             build_train_step, flax_apply, init_state)

M, S, LAYERS = 16, 4, 8
mesh = MeshSpec(stage=S).build()
tokens = jnp.zeros((M, 512), jnp.int32)

def report(tag, interleave, gpipe=False):
    model = GPT2Pipelined(vocab_size=256, layers=LAYERS, dim=256, heads=4,
                          max_seq=512, dtype='float32', microbatches=M,
                          mesh=mesh, interleave=interleave)
    state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)
    step_fn = (build_train_step(flax_apply(model), NextTokenLoss(),
                                SGD(lr=0.1), jit=False) if gpipe else
               build_1f1b_train_step(model, NextTokenLoss(), SGD(lr=0.1),
                                     jit=False))
    lowered = jax.jit(step_fn, donate_argnums=0).lower(state, tokens, tokens)
    mem = lowered.compile().memory_analysis()
    print(tag, 'temp MB:', round(mem.temp_size_in_bytes / 2**20, 1),
          'total MB:', round((mem.temp_size_in_bytes + mem.argument_size_in_bytes) / 2**20, 1))

report('gpipe+autodiff     ', 1, gpipe=True)
report('1f1b               ', 1)
report('1f1b interleave=2  ', 2)

# schedule accounting (per device, one step): busy chunk-units vs total
# tick capacity. A chunk-unit for interleave=v is 1/v of a stage-unit, so
# idle time is comparable across rows after dividing by v.
print('\nschedule: ticks x unit-cost, idle fraction of the fwd slot')
for v in (1, 2, 4):
    if LAYERS % (v * S):
        continue
    rounds = v * M + v * S + S - 2
    busy = v * M
    print(f'interleave={v}: {rounds} ticks of 1/{v} stage-unit, '
          f'fwd-slot idle {rounds - busy} chunk-ticks '
          f'= {(rounds - busy) / v:.1f} stage-equivalents '
          f'(bubble fraction {(rounds - busy) / rounds:.2%})')
