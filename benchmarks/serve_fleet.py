"""Fleet recovery cost: kill a replica mid-stream → first rerouted token.

The fleet tier's promise (``tpusystem/serve/fleet.py``) measured: 3
replicas serve a mixed workload, one is "killed" mid-stream (its handle's
kill seam — the in-process stand-in for SIGKILL; the journal lives in a
supervisor-side :class:`~tpusystem.checkpoint.memstore.MemStore` that
outlives it), and recovery is timed from the kill to the **first token a
rerouted request emits on a surviving replica**, two ways:

1. ``hot``  — the router recovers the dead replica's journal through the
             preference chain and redistributes: seated rows re-prefill
             ``prompt + emitted prefix`` on a survivor and resume;
2. ``cold`` — no recoverable journal: the router's own routing table
             re-submits every open request raw (what the handoff costs
             without the journal — the cadence-gap path).

Both arms pay the same redistribution plumbing; the hot arm's rerouted
rows resume mid-budget while the cold arm re-decodes every
already-delivered token before the fleet drains — ``drain_seconds``
shows that tail. Greedy decode is deterministic, so both arms finish
token-exact against an uninterrupted fleet (asserted every trial).

Every row is one machine-readable JSON line (the ``serve_recovery.py``
convention); the LAST line is the ``fleet_recovery_seconds`` headline
``bench.py`` forwards (value = hot first-token seconds, cold arm
alongside). CPU numbers are smoke; the TPU protocol rides the same
script (BASELINE.md "serve protocol" sizing caveats apply).

Run: ``python benchmarks/serve_fleet.py [headline]``.
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.checkpoint.memstore import MemStore
from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.serve import (Engine, ReplicaHandle, Request, Router,
                             Scheduler, ServingReplica)

TRIALS = 3
REPLICAS = 3
ROWS = 2
KILL_TICK = 3
ON_TPU = jax.default_backend() in ('tpu', 'axon')


def recipe():
    """Model + workload (the ``serve_recovery.py`` sizing discipline):
    more requests than the fleet's rows, so the killed replica holds
    seated AND queued work — both handoff flavors exercised."""
    if ON_TPU:
        module = GPT2(dropout=0.0, vocab_size=50304, max_seq=512)
        lengths, vocab = (16, 32, 64, 96), 50257
        budgets = (24, 24, 24, 96, 24, 24, 24, 96, 24)
    else:
        module = gpt2_tiny(dtype='float32', layers=4, dim=256, heads=8,
                           vocab_size=1024, max_seq=256)
        lengths, vocab = (4, 8, 16, 24), 1024
        budgets = (12, 12, 12, 48, 12, 12, 12, 48, 12)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (lengths[i % len(lengths)],))
               .astype(np.int32).tolist() for i in range(len(budgets))]
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray([prompts[0]], jnp.int32))['params']
    return module, params, prompts, list(budgets)


def build_fleet(module, params, *, journaled):
    """3 replicas, each journaling every tick into its supervisor-RAM
    store (hot arm) or not at all (cold arm: the router's routing table
    is the only survivor of a kill)."""
    handles = []
    for i in range(REPLICAS):
        store = MemStore() if journaled else None
        build = lambda: Scheduler(Engine(module, params, rows=ROWS,
                                         block_size=16 if ON_TPU else 8))
        handles.append(ReplicaHandle(ServingReplica(
            build, identity=f'rep{i}', client=store, cadence=1)))
    return Router(handles), handles


def submit_all(router, prompts, budgets):
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        router.submit(Request(f'r{index}', prompt, budget))


def trial(module, params, prompts, budgets, reference, *, journaled):
    """One kill-mid-stream run: returns (first rerouted token seconds,
    drain seconds, hot reroutes, cold reroutes), token-exactness of the
    WHOLE workload asserted against the uninterrupted reference."""
    router, handles = build_fleet(module, params, journaled=journaled)
    submit_all(router, prompts, budgets)
    killed_at = None
    rerouted_ids: set = set()
    first = drained = None
    hot = cold = 0
    for _ in range(10_000):
        if router.idle:
            break
        if router.ticks + 1 == KILL_TICK:
            handles[0].kill()
            killed_at = time.perf_counter()
        tick = router.step()
        for event in tick.rerouted:
            rerouted_ids.add(event.id)
            hot += event.where == 'hot'
            cold += event.where == 'cold'
        if (first is None and killed_at is not None
                and rerouted_ids & set(tick.emitted)):
            first = time.perf_counter() - killed_at
    drained = time.perf_counter() - killed_at
    assert router.idle and rerouted_ids, 'the kill rerouted nothing'
    for rid, completion in router.results.items():
        expected = reference[rid].tokens
        assert completion.tokens == expected, (
            f'{rid} diverged across the handoff: {completion.tokens} vs '
            f'{expected}')
    return first, drained, hot, cold


def main() -> None:
    module, params, prompts, budgets = recipe()

    # the uninterrupted fleet: every request's full greedy output
    router, _ = build_fleet(module, params, journaled=True)
    submit_all(router, prompts, budgets)
    reference = router.run_until_idle()

    hot_firsts, hot_drains = [], []
    cold_firsts, cold_drains = [], []
    flavors = None
    for _ in range(TRIALS):
        first, drain, hot, cold = trial(module, params, prompts, budgets,
                                        reference, journaled=True)
        hot_firsts.append(first)
        hot_drains.append(drain)
        flavors = (hot, cold)
        first, drain, _hot, _cold = trial(module, params, prompts, budgets,
                                          reference, journaled=False)
        cold_firsts.append(first)
        cold_drains.append(drain)

    median = lambda times: sorted(times)[len(times) // 2]
    workload = (f'{len(prompts)} reqs over {REPLICAS} replicas, 1 killed '
                f'at tick {KILL_TICK}')
    print(json.dumps({'metric': 'fleet_recovery_cold_seconds',
                      'value': round(median(cold_firsts), 4),
                      'unit': 's kill -> first rerouted token (no journal:'
                              ' routing-table cold re-submit)',
                      'drain_seconds': round(median(cold_drains), 4)}))
    print(json.dumps({
        'metric': 'fleet_recovery_seconds',
        'value': round(median(hot_firsts), 4),
        'unit': f's kill -> first rerouted token ({workload}; journal '
                f'handoff {flavors[0]} hot / {flavors[1]} cold)'
                + ('' if ON_TPU else ' [CPU smoke]'),
        'cold_seconds': round(median(cold_firsts), 4),
        'hot_drain_seconds': round(median(hot_drains), 4),
        'cold_drain_seconds': round(median(cold_drains), 4),
    }))


if __name__ == '__main__':
    main()        # 'headline' arg tolerated: every section prints anyway
