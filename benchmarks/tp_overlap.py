"""Latency-hiding TP collectives: the three-way decomposition sweep.

BASELINE.md's 8B projection subtracts ICI collective time because every
Megatron TP layer lets GSPMD emit a monolithic all-gather before the
up-projection and a monolithic reduce-scatter after the down-projection,
serializing transfer against the MXU. This benchmark times the
sequence-sharded TP FFN's phases three ways at each shape — the
moe_ceiling-style per-phase table:

  ag_mm[gspmd]       partitioner-inserted all-gather + matmul
  ag_mm[one-shot]    manual shard_map: lax.all_gather, then the matmul
  ag_mm[overlap cN]  decomposed ring (parallel/overlap.py), N ppermute
                     chunks per hop
  mm_rs[...]         the reduce-scatter dual, same three ways
  ffn[...]           the whole up -> gelu -> down block, same three ways

All rows are fwd+bwd with the conv_ceiling data-chained discipline (the
loss is a sum of squares, every gradient folds back into the carried
inputs — nothing hoists or DCEs). `python benchmarks/tp_overlap.py`
prints the table + summary; `... headline` prints the single JSON line
`bench.py` forwards (`tp_ffn_overlap_speedup_vs_gspmd`).

Hardware: uses the real accelerator mesh when >= 2 devices are present
(real numbers); otherwise re-execs itself onto an 8-device virtual CPU
mesh at smoke shapes — same code paths, scheduler-free numbers that only
smoke-test the sweep (BASELINE.md "tp_overlap protocol").
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import functools
import json
import os
import time

if os.environ.get('_TP_OVERLAP_VIRTUAL'):
    from tpusystem.parallel import force_host_platform
    force_host_platform(8)

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bench import materialize as _materialize


def _ensure_devices():
    """Real accelerator mesh when it exists; else re-exec onto the
    virtual CPU mesh (force_host_platform must precede backend init, so
    a fresh process is the only clean path)."""
    devices = jax.devices()
    if devices[0].platform != 'cpu' and len(devices) >= 2:
        return devices, False
    if devices[0].platform == 'cpu' and len(devices) >= 4:
        return devices, True
    env = dict(os.environ)
    env['_TP_OVERLAP_VIRTUAL'] = '1'
    flag = '--xla_force_host_platform_device_count'
    if flag not in env.get('XLA_FLAGS', ''):
        env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') + f' {flag}=8').strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


DEVICES, VIRTUAL = _ensure_devices()
RING = max(size for size in (2, 4) if size <= len(DEVICES))
# smoke shapes on the virtual mesh (XLA:CPU has no latency-hiding
# scheduler — the rows only prove the sweep runs); real shapes on chips
TOKENS, DIM, FFN, REPS = ((512, 256, 1024, 5) if VIRTUAL
                          else (8192, 4096, 14336, 20))
CHUNK_COUNTS = (1, 2, 4)


def _chain_scalar(tree):
    total = jnp.float32(0)
    for leaf in jax.tree.leaves(tree):
        total = total + leaf.reshape(-1)[0].astype(jnp.float32)
    return total


def time_fwd_bwd(fn, *args) -> float:
    """Seconds per fwd+bwd over REPS chained iterations (the
    benchmarks/README.md methodology: square loss, gradients folded back
    into the carry, completion forced by a host read)."""
    def loss_fn(*a):
        out = fn(*a)
        return jnp.sum(jnp.square(out.astype(jnp.float32))) * 1e-9

    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(len(args))))

    def body(_, carry):
        loss, grads = vg(*carry)
        feedback = (loss + _chain_scalar(grads)) * 1e-7
        return tuple(a + feedback.astype(a.dtype) for a in carry)

    run = jax.jit(lambda *a: lax.fori_loop(0, REPS, body, a))
    out = run(*args)
    _materialize(out)
    t0 = time.perf_counter()
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


def _report(tag, seconds, note=None):
    entry = {'phase': tag, 'us': round(seconds * 1e6, 1)}
    if note:
        entry['note'] = note
    print(json.dumps(entry))
    return seconds


def _build():
    from tpusystem.parallel.mesh import MODEL, MeshSpec, shard_map
    from tpusystem.parallel.overlap import (allgather_matmul,
                                            matmul_reducescatter)

    mesh = MeshSpec(model=RING).build(DEVICES[:RING])
    rng = np.random.default_rng(0)
    dtype = jnp.bfloat16
    x = jnp.asarray(rng.normal(size=(TOKENS, DIM)) * 0.1, dtype)
    grown_ref = jnp.asarray(rng.normal(size=(TOKENS, FFN)) * 0.1, dtype)
    w_up = jnp.asarray(rng.normal(size=(DIM, FFN)) * 0.02, dtype)
    w_down = jnp.asarray(rng.normal(size=(FFN, DIM)) * 0.02, dtype)

    def put(value, spec):
        return jax.device_put(value, NamedSharding(mesh, spec))

    def constrained(value, spec):
        return lax.with_sharding_constraint(value, NamedSharding(mesh, spec))

    # operands pre-placed the Megatron way: activations sequence-sharded
    # over model rows, up kernel column-split, down kernel row-split
    x_rows = put(x, P(MODEL, None))
    grown_cols = put(grown_ref, P(None, MODEL))
    up_cols = put(w_up, P(None, MODEL))
    down_rows = put(w_down, P(MODEL, None))

    def manual(body, in_specs, out_specs):
        return shard_map(body, mesh=mesh, check_vma=False,
                         in_specs=in_specs, out_specs=out_specs)

    cases = {}

    # --- all-gather + matmul (the up-projection) ------------------------
    cases['ag_mm[gspmd]'] = (
        lambda xs, ws: constrained(jnp.matmul(xs, ws), P(None, MODEL)),
        (x_rows, up_cols), 'partitioner-inserted monolithic all-gather')
    cases['ag_mm[one-shot]'] = (
        manual(lambda xs, ws: jnp.matmul(
            lax.all_gather(xs, MODEL, axis=0, tiled=True), ws),
            (P(MODEL, None), P(None, MODEL)), P(None, MODEL)),
        (x_rows, up_cols), 'manual all_gather, then the matmul')
    for chunks in CHUNK_COUNTS:
        cases[f'ag_mm[overlap c{chunks}]'] = (
            manual(functools.partial(allgather_matmul, axis=MODEL,
                                     chunks=chunks),
                   (P(MODEL, None), P(None, MODEL)), P(None, MODEL)),
            (x_rows, up_cols), 'ring partials, transfers under matmuls')

    # --- matmul + reduce-scatter (the down-projection) ------------------
    cases['mm_rs[gspmd]'] = (
        lambda gs, ws: constrained(jnp.matmul(gs, ws), P(MODEL, None)),
        (grown_cols, down_rows), 'partitioner-inserted reduce-scatter')
    cases['mm_rs[one-shot]'] = (
        manual(lambda gs, ws: lax.psum_scatter(
            jnp.matmul(gs, ws), MODEL, scatter_dimension=0, tiled=True),
            (P(None, MODEL), P(MODEL, None)), P(MODEL, None)),
        (grown_cols, down_rows), 'matmul, then monolithic psum_scatter')
    for chunks in CHUNK_COUNTS:
        cases[f'mm_rs[overlap c{chunks}]'] = (
            manual(functools.partial(matmul_reducescatter, axis=MODEL,
                                     chunks=chunks),
                   (P(None, MODEL), P(MODEL, None)), P(MODEL, None)),
            (grown_cols, down_rows), 'ring-shifted running sum under matmuls')

    # --- the whole FFN block --------------------------------------------
    def ffn_gspmd(xs, wu, wd):
        grown = constrained(nn.gelu(jnp.matmul(xs, wu)), P(None, MODEL))
        return constrained(jnp.matmul(grown, wd), P(MODEL, None))

    cases['ffn[gspmd]'] = (ffn_gspmd, (x_rows, up_cols, down_rows),
                           'monolithic collectives at both ends')

    def ffn_one_shot(xs, wu, wd):
        grown = nn.gelu(jnp.matmul(
            lax.all_gather(xs, MODEL, axis=0, tiled=True), wu))
        return lax.psum_scatter(jnp.matmul(grown, wd), MODEL,
                                scatter_dimension=0, tiled=True)

    cases['ffn[one-shot]'] = (
        manual(ffn_one_shot, (P(MODEL, None), P(None, MODEL),
                              P(MODEL, None)), P(MODEL, None)),
        (x_rows, up_cols, down_rows), 'manual monolithic collectives')

    def ffn_overlap(chunks):
        def body(xs, wu, wd):
            grown = nn.gelu(allgather_matmul(xs, wu, MODEL, chunks=chunks))
            return matmul_reducescatter(grown, wd, MODEL, chunks=chunks)
        return body

    for chunks in CHUNK_COUNTS:
        cases[f'ffn[overlap c{chunks}]'] = (
            manual(ffn_overlap(chunks),
                   (P(MODEL, None), P(None, MODEL), P(MODEL, None)),
                   P(MODEL, None)),
            (x_rows, up_cols, down_rows),
            'both rings, transfers hidden under partial matmuls')

    return cases


def sweep() -> dict[str, float]:
    times = {}
    for tag, (fn, args, note) in _build().items():
        times[tag] = _report(tag, time_fwd_bwd(fn, *args), note=note)
    best_chunks, best = min(
        ((chunks, times[f'ffn[overlap c{chunks}]']) for chunks in CHUNK_COUNTS),
        key=lambda pair: pair[1])
    print(json.dumps({'summary': {
        'mesh': f"{DEVICES[0].platform} model={RING}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'tokens': TOKENS, 'dim': DIM, 'ffn': FFN,
        'ffn_us': {tag.split('[')[1][:-1]: round(times[tag] * 1e6, 1)
                   for tag in times if tag.startswith('ffn[')},
        'best_overlap_chunks': best_chunks,
        'overlap_vs_gspmd': round(times['ffn[gspmd]'] / best, 3),
        'overlap_vs_one_shot': round(times['ffn[one-shot]'] / best, 3),
    }}))
    return times


def headline() -> None:
    """The single JSON line bench.py forwards as its tp_overlap row."""
    cases = _build()
    picks = ['ffn[gspmd]'] + [f'ffn[overlap c{c}]' for c in CHUNK_COUNTS]
    times = {tag: time_fwd_bwd(cases[tag][0], *cases[tag][1])
             for tag in picks}
    best_chunks, best = min(
        ((chunks, times[f'ffn[overlap c{chunks}]']) for chunks in CHUNK_COUNTS),
        key=lambda pair: pair[1])
    speedup = times['ffn[gspmd]'] / best
    print(json.dumps({
        'metric': 'tp_ffn_overlap_speedup_vs_gspmd',
        'value': round(speedup, 4),
        'unit': 'x',
        'mesh': f"{DEVICES[0].platform} model={RING}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'chunks': best_chunks,
        'gspmd_us': round(times['ffn[gspmd]'] * 1e6, 1),
        'overlap_us': round(best * 1e6, 1),
    }))


if __name__ == '__main__':
    if 'headline' in sys.argv[1:]:
        headline()
    else:
        sweep()
