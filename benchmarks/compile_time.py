"""Compile time vs depth: unrolled layer loop vs scan_layers (stacked
block params + lax.scan) on the Llama family — BASELINE.md scan-layers
numbers.

Measures lower+compile wall seconds of the full fwd+bwd train step on
ABSTRACT inputs (`jax.eval_shape` state, `.lower(...).compile()`), so no
parameter memory is materialized and the 8B-scale shape compiles on the
host. XLA:CPU and XLA:TPU both scale with HLO size, which is what the
unrolled loop inflates linearly in depth.
"""
import sys, time, json, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
from tpusystem.parallel import force_host_platform
force_host_platform(1)

import jax, jax.numpy as jnp

from tpusystem.models import Llama
from tpusystem.train import (AdamW, ChunkedNextTokenLoss, build_train_step,
                             flax_apply, init_state)


def compile_seconds(scan: bool, layers: int, dim=2048, ffn=7168, heads=16,
                    kv_heads=8, vocab=32000, seq=1024, batch=2):
    module = Llama(vocab_size=vocab, layers=layers, dim=dim, heads=heads,
                   kv_heads=kv_heads, ffn_dim=ffn, max_seq=seq, remat=True,
                   return_features=True, scan_layers=scan)
    optimizer = AdamW(lr=1e-4)
    tokens = jnp.zeros((batch, seq), jnp.int32)
    state = jax.eval_shape(
        lambda: init_state(module, optimizer, tokens[:1, :8]))
    step = build_train_step(flax_apply(module),
                            ChunkedNextTokenLoss(chunks=4, tied=False),
                            optimizer, jit=False)
    start = time.perf_counter()
    lowered = jax.jit(step, donate_argnums=0).lower(state, tokens, tokens)
    lower_s = time.perf_counter() - start
    start = time.perf_counter()
    lowered.compile()
    compile_s = time.perf_counter() - start
    print(json.dumps({'scan_layers': scan, 'layers': layers,
                      'lower_s': round(lower_s, 1),
                      'compile_s': round(compile_s, 1)}))
    return compile_s


for layers in (8, 16, 32):
    unrolled = compile_seconds(False, layers)
    scanned = compile_seconds(True, layers)
    print(f'layers={layers}: unrolled {unrolled:.1f}s, '
          f'scanned {scanned:.1f}s ({unrolled / scanned:.1f}x)')
