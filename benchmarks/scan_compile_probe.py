"""Probe: where does the scan+Pallas AOT compile time go on the relay?

Round-3 finding: the headline bench keeps the unrolled stack because the
relay's AOT compiler took ~500 s on the scan+Pallas composition (XLA:CPU
compiles the same program in seconds). This probe times ``lower()`` and
``compile()`` separately for one composition so the slow axis (scan,
flash kernel, remat, steps-loop) can be bisected.

Run (one composition per process — a hung compile shouldn't block the
rest): ``python benchmarks/scan_compile_probe.py [scan] [flash] [remat]
[loop] [layers=N]``
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def main(argv: list[str]) -> None:
    from tpusystem.models import GPT2
    from tpusystem.train import (AdamW, ChunkedNextTokenLoss,
                                 build_train_step, flax_apply, init_state)

    scan = 'scan' in argv
    flash = 'flash' in argv
    remat = 'remat' in argv
    loop = 'loop' in argv           # steps-loop like bench.py
    layers = next((int(a.split('=')[1]) for a in argv
                   if a.startswith('layers=')), 12)
    steps = next((int(a.split('=')[1]) for a in argv
                  if a.startswith('steps=')), 90)
    outer = next((a.split('=')[1] for a in argv
                  if a.startswith('outer=')), 'fori')
    unit = next((int(a.split('=')[1]) for a in argv
                 if a.startswith('unit=')), 1)

    module = GPT2(dropout=0.0, vocab_size=50304, return_features=True,
                  layers=layers, scan_layers=scan, scan_unit=unit,
                  attention='flash' if flash else 'xla', remat=remat)
    optimizer = AdamW(lr=3e-4, grad_clip=1.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 50257, (16, 1024)), jnp.int32)
    state = init_state(module, optimizer, tokens[:1, :8])
    step = build_train_step(flax_apply(module),
                            ChunkedNextTokenLoss(chunks=8), optimizer,
                            jit=False)

    if loop and outer == 'scan':
        @partial(jax.jit, donate_argnums=0)
        def target(state, tokens):
            final, _ = jax.lax.scan(
                lambda st, _: (step(st, tokens, tokens)[0], None),
                state, None, length=steps)
            return final
    elif loop:
        @partial(jax.jit, donate_argnums=0)
        def target(state, tokens):
            return jax.lax.fori_loop(
                0, steps, lambda i, st: step(st, tokens, tokens)[0], state)
    else:
        target = jax.jit(step, donate_argnums=0)

    t0 = time.perf_counter()
    lowered = target.lower(state, tokens, tokens) if not loop \
        else target.lower(state, tokens)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    del compiled
    print(f'scan={scan} flash={flash} remat={remat} loop={loop} '
          f'steps={steps} outer={outer} layers={layers} unit={unit}: '
          f'lower {t1 - t0:7.1f}s  compile {t2 - t1:7.1f}s')


if __name__ == '__main__':
    main(sys.argv[1:])
