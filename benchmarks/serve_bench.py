"""Serving throughput: continuous batching vs static padded batching.

The serving engine's reason to exist, measured: a mixed-length synthetic
workload (short and long generations interleaved, the shape real traffic
has) served two ways —

1. ``static``     — classic padded batching: requests grouped in arrival
                    order into fixed batches of ``ROWS``, prompts padded
                    to the workload's widest bucket, every row decoded to
                    its group's LONGEST request (the whole batch waits on
                    the straggler; short rows burn steps on tokens nobody
                    asked for). One ``generate()`` call per group — all
                    groups share one compiled program.
2. ``continuous`` — the paged engine (`tpusystem/serve/`): iteration-
                    level scheduling admits a queued request the moment a
                    row frees, so a retired short request's row is
                    immediately producing a new request's tokens instead
                    of padding out the straggler.

Tokens/sec counts only **delivered** tokens (what each request asked
for) over wall time, so the static arm pays for its dead rows. Per-phase
rows decompose the continuous arm (prefill / admit / decode dispatch
time from the engine's own counters).

A third arm measures **radix prefix sharing** (``shared``): N requests
that open with one long system prompt and differ only in a short user
suffix — the shape RAG/chat traffic has — served with
``share_prefix=True`` vs without. With sharing, admission adopts the
cached prefix blocks and prefills only the uncached suffix, so the
prefill cost per request collapses from ``bucket(prefix + suffix)`` to
``bucket(suffix)``; the ``prefix_hit_rate`` row reports the fraction of
prompt tokens adopted and every completion is asserted token-exact
against standalone ``generate()``.

Every row is one machine-readable JSON line (the ``decode_roofline.py``
convention); the LAST line is the ``serve_tok_s`` headline ``bench.py``
forwards, and the ``serve_shared_prefix_speedup`` row is forwarded as
its own ``bench.py`` line. On CPU the numbers are smoke (documented in
BASELINE.md "serve protocol" and "shared-prefix serve protocol" — the
TPU protocol uses the 125M decode config); the *ratios* are the
architectural claims: continuous batching >= 2x static, and sharing
>= 1.5x no-sharing delivered tok/s on the shared-prompt workload.

A fourth arm measures **seeded sampling** (``sampled``): the same mixed
workload served greedy vs with per-request seeded top-k/top-p
``SamplingParams`` on ONE engine (one compiled trace for both arms) —
the cost of counter-based sampling inside the compiled step, with
determinism asserted bitwise every trial (each timed pass is re-run
with the same seeds and compared token-for-token).

Run: ``python benchmarks/serve_bench.py [headline|shared|sampled]`` —
``shared`` prints only the prefix-sharing section (its last line is the
``serve_shared_prefix_speedup`` row ``bench.py`` forwards); ``sampled``
prints only the sampling section (last line ``serve_sampled_tok_s``).
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import materialize
from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.serve import Engine, Request, SamplingParams, Scheduler
from tpusystem.train import generate

TRIALS = 3
ROWS = 4
ON_TPU = jax.default_backend() in ('tpu', 'axon')


def recipe():
    """Model + workload. TPU: the BASELINE decode config (125M). CPU:
    tiny GPT-2 — smoke numbers, real ratio."""
    if ON_TPU:
        module = GPT2(dropout=0.0, vocab_size=50304, max_seq=512)
        lengths, vocab = (16, 32, 64, 96), 50257
        budgets = (16, 16, 16, 96) * 3          # short x3 : 1 straggler
    else:
        # big enough that a decode step is compute-bound, not dispatch-
        # bound (the tiny preset hides the batching win behind CPU
        # per-dispatch overhead — measured 1.2 ms/step static scan vs
        # 3 ms/step engine dispatch at dim 64)
        module = gpt2_tiny(dtype='float32', layers=4, dim=256, heads=8,
                           vocab_size=1024, max_seq=256)
        lengths, vocab = (4, 8, 16, 24), 1024
        budgets = (8, 8, 8, 64) * 3
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (lengths[i % len(lengths)],))
               .astype(np.int32) for i in range(len(budgets))]
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray(prompts[0][None]))['params']
    return module, params, prompts, list(budgets)


def static_arm(module, params, prompts, budgets) -> tuple[float, int]:
    """Median wall seconds for the whole workload, padded-batch style,
    plus delivered tokens. All groups pad prompts to the workload's
    widest prompt and decode to the group's longest budget."""
    width = max(len(p) for p in prompts)
    groups = [slice(i, i + ROWS) for i in range(0, len(prompts), ROWS)]

    def run_once() -> None:
        for group in groups:
            batch_prompts = prompts[group]
            batch_budgets = budgets[group]
            padded = np.zeros((len(batch_prompts), width), np.int32)
            for row, prompt in enumerate(batch_prompts):
                padded[row, :len(prompt)] = prompt
            out = generate(module, params, jnp.asarray(padded),
                           steps=max(batch_budgets))
            materialize(out)

    run_once()                                   # warm/compile
    trials = []
    for _ in range(TRIALS):
        start = time.perf_counter()
        run_once()
        trials.append(time.perf_counter() - start)
    return sorted(trials)[len(trials) // 2], sum(budgets)


def continuous_arm(module, params, prompts, budgets) -> tuple[float, int, dict]:
    """Median wall seconds through the paged engine + scheduler, plus
    delivered tokens and the engine's per-phase dispatch seconds from
    the LAST trial (fresh counters per trial)."""
    engine = Engine(module, params, rows=ROWS,
                    block_size=16 if ON_TPU else 8)

    def run_once() -> dict:
        engine.timings = {'prefill': 0.0, 'admit': 0.0, 'step': 0.0}
        scheduler = Scheduler(engine)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            scheduler.submit(Request(f'r{index}', list(prompt), budget))
        results = scheduler.run()
        delivered = sum(len(c.tokens) for c in results.values())
        assert delivered == sum(budgets), (delivered, sum(budgets))
        return dict(engine.timings)

    run_once()                                   # warm/compile
    trials, phases = [], {}
    for _ in range(TRIALS):
        start = time.perf_counter()
        phases = run_once()
        trials.append(time.perf_counter() - start)
    return sorted(trials)[len(trials) // 2], sum(budgets), phases


def shared_recipe():
    """Model + shared-prompt workload: one long system prefix, short
    per-request suffixes. TPU: the BASELINE decode config. CPU: the
    dim-256 preset (same reasoning as :func:`recipe` — dispatch-bound
    tiny models hide the prefill win)."""
    if ON_TPU:
        module = GPT2(dropout=0.0, vocab_size=50304, max_seq=512)
        prefix_len, vocab, max_new = 384, 50257, 16
    else:
        module = gpt2_tiny(dtype='float32', layers=4, dim=256, heads=8,
                           vocab_size=1024, max_seq=256)
        prefix_len, vocab, max_new = 192, 1024, 8
    rng = np.random.default_rng(7)
    prefix = rng.integers(0, vocab, (prefix_len,)).astype(np.int32)
    prompts = [np.concatenate([prefix, rng.integers(0, vocab, (8,))
                               .astype(np.int32)]) for _ in range(8)]
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray(prompts[0][None]))['params']
    return module, params, prompts, max_new


def shared_arm(module, params, prompts, max_new,
               share: bool) -> tuple[float, int, float]:
    """Median wall seconds for the shared-prompt workload through the
    scheduler with prefix sharing on or off, plus delivered tokens and
    the engine-lifetime prefix hit rate. ONE engine per arm: the warmup
    run compiles AND (sharing arm) populates the radix tree, so the
    timed trials measure the steady state a long-lived replica serves
    from — every trial's prefix blocks adopted, only suffixes
    prefilled."""
    engine = Engine(module, params, rows=ROWS, block_size=16,
                    share_prefix=share)

    def run_once() -> None:
        scheduler = Scheduler(engine)
        for index, prompt in enumerate(prompts):
            scheduler.submit(Request(f's{index}', list(prompt), max_new))
        results = scheduler.run()
        delivered = sum(len(c.tokens) for c in results.values())
        assert delivered == max_new * len(prompts)

    run_once()                                   # warm/compile + warm tree
    trials = []
    for _ in range(TRIALS):
        start = time.perf_counter()
        run_once()
        trials.append(time.perf_counter() - start)
    tokens = max_new * len(prompts)
    return (sorted(trials)[len(trials) // 2], tokens,
            engine.prefix_hit_rate() if share else 0.0)


def check_shared_parity(module, params, prompts, max_new) -> None:
    """Every sharing-arm completion must be exactly generate()'s."""
    engine = Engine(module, params, rows=ROWS, block_size=16,
                    share_prefix=True)
    scheduler = Scheduler(engine)
    for index, prompt in enumerate(prompts):
        scheduler.submit(Request(f's{index}', list(prompt), max_new))
    results = scheduler.run()
    for index, prompt in enumerate(prompts):
        ref = generate(module, params, jnp.asarray(prompt)[None],
                       steps=max_new)
        expect = [int(t) for t in np.asarray(ref)[0, len(prompt):]]
        got = list(results[f's{index}'].tokens)
        assert got == expect, (index, got, expect)


def shared_section() -> None:
    module, params, prompts, max_new = shared_recipe()
    check_shared_parity(module, params, prompts, max_new)
    cold_seconds, tokens, _ = shared_arm(module, params, prompts, max_new,
                                         share=False)
    warm_seconds, _, hit_rate = shared_arm(module, params, prompts, max_new,
                                           share=True)
    cold_tok_s = tokens / cold_seconds
    warm_tok_s = tokens / warm_seconds
    workload = (f'{len(prompts)} reqs, shared prefix '
                f'{len(prompts[0]) - 8}, suffix 8, max_new {max_new}, '
                f'rows {ROWS}')
    print(json.dumps({'metric': 'serve_prefix_hit_rate',
                      'value': round(hit_rate, 3),
                      'unit': 'shared/prompt tokens', 'workload': workload}))
    print(json.dumps({
        'metric': 'serve_shared_prefix_speedup',
        'value': round(warm_tok_s / cold_tok_s, 2),
        'unit': 'x delivered tok/s vs no-sharing'
                + ('' if ON_TPU else ' [CPU smoke]'),
        'shared_tok_s': round(warm_tok_s, 1),
        'unshared_tok_s': round(cold_tok_s, 1),
        'workload': workload}))


def sampled_arm(engine, prompts, budgets, sampling) -> tuple[float, int]:
    """Median wall seconds for the workload with ``sampling(index)``
    per request (None entries = greedy), plus delivered tokens. EVERY
    trial runs the workload twice and asserts the two passes bitwise-
    identical — the determinism contract is measured under the clock,
    not assumed (the second pass is outside the timed window)."""

    def run_once() -> dict:
        scheduler = Scheduler(engine)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            scheduler.submit(Request(f'r{index}', list(prompt), budget,
                                     sampling=sampling(index)))
        return {rid: list(c.tokens) for rid, c in scheduler.run().items()}

    run_once()                                   # warm/compile
    trials = []
    for _ in range(TRIALS):
        start = time.perf_counter()
        first = run_once()
        trials.append(time.perf_counter() - start)
        again = run_once()                       # same seeds -> same bits
        assert first == again, 'sampled decode was not deterministic'
    return sorted(trials)[len(trials) // 2], sum(budgets)


def sampled_section() -> None:
    """Sampled vs greedy delivered tok/s on the mixed workload — the
    cost of per-row seeded top-k/top-p sampling inside the one compiled
    step (same engine, same trace), with determinism asserted every
    trial. LAST line = ``serve_sampled_tok_s`` (``bench.py`` forwards
    it)."""
    module, params, prompts, budgets = recipe()
    engine = Engine(module, params, rows=ROWS,
                    block_size=16 if ON_TPU else 8)
    greedy_seconds, tokens = sampled_arm(engine, prompts, budgets,
                                         lambda index: None)
    sampled_seconds, _ = sampled_arm(
        engine, prompts, budgets,
        lambda index: SamplingParams(seed=100 + index, temperature=0.9,
                                     top_k=64, top_p=0.95))
    assert engine.trace_count == 1, engine.trace_count
    greedy_tok_s = tokens / greedy_seconds
    sampled_tok_s = tokens / sampled_seconds
    workload = (f'{len(prompts)} reqs, prompts '
                f'{sorted(set(len(p) for p in prompts))}, budgets '
                f'{sorted(set(budgets))}, rows {ROWS}')
    print(json.dumps({
        'metric': 'serve_sampled_tok_s',
        'value': round(sampled_tok_s, 1),
        'unit': f'tok/s delivered, seeded top-k/top-p ({workload})'
                + ('' if ON_TPU else ' [CPU smoke]'),
        'greedy_tok_s': round(greedy_tok_s, 1),
        'sampled_over_greedy': round(sampled_tok_s / greedy_tok_s, 2),
        'determinism': 'asserted bitwise every trial'}))


def main() -> None:
    if 'shared' in sys.argv[1:]:
        shared_section()         # LAST line = serve_shared_prefix_speedup
        return
    if 'sampled' in sys.argv[1:]:
        sampled_section()        # LAST line = serve_sampled_tok_s
        return
    shared_section()
    sampled_section()
    module, params, prompts, budgets = recipe()
    static_seconds, tokens = static_arm(module, params, prompts, budgets)
    continuous_seconds, _, phases = continuous_arm(module, params, prompts,
                                                   budgets)
    static_tok_s = tokens / static_seconds
    continuous_tok_s = tokens / continuous_seconds
    workload = (f'{len(prompts)} reqs, prompts '
                f'{sorted(set(len(p) for p in prompts))}, budgets '
                f'{sorted(set(budgets))}, rows {ROWS}')
    print(json.dumps({'metric': 'serve_static_tok_s',
                      'value': round(static_tok_s, 1), 'unit': 'tok/s',
                      'seconds': round(static_seconds, 3),
                      'workload': workload}))
    for phase, seconds in phases.items():
        print(json.dumps({'metric': f'serve_phase_{phase}_s',
                          'value': round(seconds, 4),
                          'unit': 's (continuous arm, one workload)'}))
    print(json.dumps({
        'metric': 'serve_tok_s',
        'value': round(continuous_tok_s, 1),
        'unit': f'tok/s delivered ({workload})'
                + ('' if ON_TPU else ' [CPU smoke]'),
        'static_tok_s': round(static_tok_s, 1),
        'speedup_vs_static': round(continuous_tok_s / static_tok_s, 2),
    }))


if __name__ == '__main__':
    main()        # 'headline' arg tolerated: every section prints anyway
