"""Capacity arbitration cost: burst -> shrunk trainer back at work.

The gang orchestrator's (`tpusystem/orchestrator/gang.py`) promise is
that a serving burst costs the trainer a *resize*, not its job — so the
number that matters is the wall clock of the whole arbitration window:

1. ``decision``  — ``request_capacity`` alone: donor selection plus the
   two-phase journal round trip (``decided`` replicated to the plane,
   the resize seam driven, ``done`` replicated) — the pure control-
   plane cost of an arbitration;
2. ``grant``     — the full burst-to-training window: the decision PLUS
   the shrunk trainer hot-resharding its state onto the granted-down
   submesh (`elastic_resume` -> ``hot-reshard``, the exit-46 relaunch's
   restore path) and taking one step there;
3. ``release``   — the ebb: the LIFO debt paid back plus the trainer's
   hot reshard back onto its full submesh and one step.

Medians of TRIALS runs on the tiny model; a fresh orchestrator + plane
per trial (grants mutate placements), compiled steps shared across
trials. On a multi-chip TPU the real devices are used; elsewhere the
CPU platform is forced to 8 virtual chips — smoke numbers, same
protocol.

Every row is one machine-readable JSON line; the LAST line is the
``arbitration_seconds`` headline ``bench.py`` forwards (value = the
full grant window; the decision-only and release arms ride alongside).

Run: ``python benchmarks/arbitration.py [headline]``.
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import os
import tempfile
import time

if os.environ.get('_ARBITRATION_VIRTUAL'):
    from tpusystem.parallel import force_host_platform
    force_host_platform(8)

import jax

TRIALS = 3


def _ensure_devices():
    """Real 8-chip mesh when it exists; else re-exec onto an 8-device
    virtual CPU mesh (force_host_platform must precede backend init, so
    a fresh process is the only clean path — the fsdp_overlap pattern)."""
    devices = jax.devices()
    if len(devices) >= 8:
        return devices[:8]
    env = dict(os.environ)
    env['_ARBITRATION_VIRTUAL'] = '1'
    env['JAX_PLATFORMS'] = 'cpu'
    flag = '--xla_force_host_platform_device_count'
    if flag not in env.get('XLA_FLAGS', ''):
        env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') + f' {flag}=8').strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


class _Runner:
    def __init__(self):
        self.resizes = []

    def poll(self):
        return None

    def resize(self, devices):
        self.resizes.append(tuple(devices))


def main() -> None:
    import jax.numpy as jnp
    import numpy as np

    from bench import materialize
    from tpusystem.checkpoint import Checkpointer
    from tpusystem.checkpoint.memstore import HotState, MemStore, blob_digest
    from tpusystem.models import gpt2_tiny
    from tpusystem.orchestrator import JobSpec, Orchestrator, Submesh
    from tpusystem.parallel import MeshSpec, TensorParallel, batch_sharding
    from tpusystem.parallel.elastic import elastic_resume, split_pieces
    from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                                 flax_apply, init_state)

    devices = _ensure_devices()
    identity = 'bench-arbitration'
    spec = MeshSpec(fsdp=4)
    module = gpt2_tiny()
    optimizer = AdamW(lr=1e-3)
    policy = TensorParallel(module.partition_rules(), fsdp=True,
                            fsdp_min_size=64)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 32)), jnp.int32)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)

    mesh4 = spec.build(devices[:4])
    mesh2 = spec.resized(2).build(devices[:2])
    state = policy.place(init_state(module, optimizer, tokens[:1]), mesh4)
    batch4 = jax.device_put(tokens, batch_sharding(mesh4))
    batch2 = jax.device_put(tokens, batch_sharding(mesh2))
    state, _ = step(state, batch4, batch4)
    at = int(state.step)
    pieces = [HotState(step=at, digest=blob_digest(blob), blob=blob)
              for blob in split_pieces(state, mesh4, hosts=4)]
    blank2 = policy.place(init_state(module, optimizer, tokens[:1]), mesh2)
    blank4 = policy.place(init_state(module, optimizer, tokens[:1]), mesh4)

    train_spec = JobSpec('train', 'train', priority=1, chips=4, min_chips=2)
    serve_spec = JobSpec('serve', 'serve', priority=2, chips=2, min_chips=2)

    decisions, grants, releases = [], [], []
    with tempfile.TemporaryDirectory() as root, \
            Checkpointer(root, async_save=False) as checkpointer:
        checkpointer.save(identity, at, state, extras={'step': at})
        for _ in range(TRIALS):
            runner = _Runner()
            orchestrator = Orchestrator(tuple(range(8)), client=MemStore())
            orchestrator.admit(train_spec, runner,
                               submesh=Submesh((0, 1, 2, 3)))
            orchestrator.admit(serve_spec, _Runner(), submesh=Submesh((4, 5)))

            start = time.perf_counter()
            orchestrator.request_capacity('serve', chips=4)
            decisions.append(time.perf_counter() - start)
            assert runner.resizes == [(0, 1)], runner.resizes
            shrunk, _, _, source = elastic_resume(
                checkpointer, identity, blank2, pieces)
            assert source == 'hot-reshard', source
            shrunk, _ = step(shrunk, batch2, batch2)
            materialize(shrunk.params)
            grants.append(time.perf_counter() - start)

            shrunk_pieces = [
                HotState(step=int(shrunk.step), digest=blob_digest(blob),
                         blob=blob)
                for blob in split_pieces(shrunk, mesh2, hosts=2)]
            start = time.perf_counter()
            returned = orchestrator.release_capacity('serve')
            assert returned == 2 and runner.resizes[-1] == (0, 1, 2, 3)
            grown, _, _, source = elastic_resume(
                checkpointer, identity, blank4, shrunk_pieces)
            assert source == 'hot-reshard', source
            grown, _ = step(grown, batch4, batch4)
            materialize(grown.params)
            releases.append(time.perf_counter() - start)

    median = lambda times: sorted(times)[len(times) // 2]  # noqa: E731
    print(json.dumps({
        'metric': 'arbitration_seconds',
        'value': round(median(grants), 4),
        'unit': 's (burst -> shrunk trainer stepping, 4->2 chips, '
                'tiny model)',
        'decision_seconds': round(median(decisions), 6),
        'release_seconds': round(median(releases), 4),
    }))


if __name__ == '__main__':
    main()        # 'headline' arg tolerated: the one row IS the headline
