"""Serving recovery cost: kill → relaunch → first replayed token.

The failover layer's promise (``tpusystem/serve/failover.py``) measured:
a serving replica mid-workload is "killed" (its engine and scheduler
abandoned — the in-process stand-in for SIGKILL; the journal lives in
the supervisor-side :class:`~tpusystem.checkpoint.memstore.MemStore`,
exactly where a real worker's pushes land), then recovery is timed from
the kill to the **first replayed token** two ways:

1. ``hot``  — the journal is recovered and each in-flight request
             re-prefills ``prompt + emitted prefix``, resuming decode
             where it died;
2. ``cold`` — no journal: every request re-submits from scratch and
             re-decodes its whole budget (what recovery costs without
             the journal — the re-submit path a truncated-replication
             outage degrades to).

Both arms pay the same engine rebuild (fresh jit of the decode step, the
bucketized prefill programs are process-cached); the hot arm's first
token arrives after ONE re-prefill per row, the cold arm additionally
re-decodes every already-delivered token before the workload finishes —
``drain_seconds`` shows that tail. Greedy decode is deterministic, so
both arms finish token-exact (asserted every trial).

Every row is one machine-readable JSON line (the ``decode_roofline.py``
convention); the LAST line is the ``serve_recovery_seconds`` headline
``bench.py`` forwards (value = hot first-token seconds, with the cold
arm alongside). CPU numbers are smoke; the TPU protocol rides the same
script (BASELINE.md "serve protocol" sizing caveats apply).

Run: ``python benchmarks/serve_recovery.py [headline]``.
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.checkpoint.memstore import MemStore
from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.serve import Engine, Request, Scheduler, ServingReplica

TRIALS = 3
ROWS = 4
KILL_TICK = 6
ON_TPU = jax.default_backend() in ('tpu', 'axon')


def recipe():
    """Model + workload (the ``serve_bench.py`` sizing discipline)."""
    if ON_TPU:
        module = GPT2(dropout=0.0, vocab_size=50304, max_seq=512)
        lengths, vocab = (16, 32, 64, 96), 50257
        budgets = (24, 24, 24, 96) * 2
    else:
        module = gpt2_tiny(dtype='float32', layers=4, dim=256, heads=8,
                           vocab_size=1024, max_seq=256)
        lengths, vocab = (4, 8, 16, 24), 1024
        budgets = (12, 12, 12, 48) * 2
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (lengths[i % len(lengths)],))
               .astype(np.int32).tolist() for i in range(len(budgets))]
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray([prompts[0]], jnp.int32))['params']
    return module, params, prompts, list(budgets)


def run_to_kill(module, params, prompts, budgets, store):
    """Serve the workload up to KILL_TICK with per-tick journal pushes,
    then abandon the replica (the kill). Returns the completions already
    delivered before the kill (reference material for the parity check)."""
    build = lambda: Scheduler(Engine(module, params, rows=ROWS,
                                     block_size=16 if ON_TPU else 8))
    replica = ServingReplica(build, identity='bench', client=store,
                             cadence=1)
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        replica.submit(Request(f'r{index}', prompt, budget))
    for _ in range(KILL_TICK):
        replica.step()
    return dict(replica.results)


def recover(module, params, prompts, budgets, store, reference):
    """Time kill -> first replayed token and kill -> fully drained, for
    one recovery arm: ``store`` holding the journal (hot) or an empty
    one (cold — the requests re-submit raw). Asserts the union of
    pre-kill and post-recovery completions is token-exact vs the
    uninterrupted reference."""
    build = lambda: Scheduler(Engine(module, params, rows=ROWS,
                                     block_size=16 if ON_TPU else 8))
    start = time.perf_counter()
    replica = ServingReplica(build, identity='bench', client=store,
                             cadence=1)
    if not replica.recovered:       # the cold arm: every request still
        # open at the kill re-submits raw (already-completed ones were
        # delivered before the kill and have nothing to recover)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            if f'r{index}' in reference:
                replica.submit(Request(f'r{index}', prompt, budget))
    first_token = None
    while not replica.idle:
        tick = replica.step()
        if first_token is None and tick is not None and (
                tick.emitted or tick.admitted):
            first_token = time.perf_counter() - start
    drained = time.perf_counter() - start
    for rid, completion in replica.results.items():
        expected = reference[rid].tokens
        assert completion.tokens == expected, (
            f'{rid} diverged after recovery: {completion.tokens} vs '
            f'{expected}')
    return first_token, drained, replica.recovered


def main() -> None:
    module, params, prompts, budgets = recipe()

    # the uninterrupted reference: every request's full greedy output
    engine = Engine(module, params, rows=ROWS,
                    block_size=16 if ON_TPU else 8)
    scheduler = Scheduler(engine)
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        scheduler.submit(Request(f'r{index}', prompt, budget))
    reference = scheduler.run()

    hot_firsts, hot_drains = [], []
    cold_firsts, cold_drains = [], []
    for _ in range(TRIALS):
        store = MemStore()
        pre_kill = run_to_kill(module, params, prompts, budgets, store)
        open_reference = {rid: completion for rid, completion
                          in reference.items() if rid not in pre_kill}
        first, drained, recovered = recover(
            module, params, prompts, budgets, store, open_reference)
        assert recovered, 'hot arm found no journal'
        hot_firsts.append(first)
        hot_drains.append(drained)
        first, drained, recovered = recover(
            module, params, prompts, budgets, MemStore(), open_reference)
        assert not recovered, 'cold arm unexpectedly found a journal'
        cold_firsts.append(first)
        cold_drains.append(drained)

    median = lambda times: sorted(times)[len(times) // 2]
    workload = (f'{len(prompts)} reqs, killed at tick {KILL_TICK}, rows '
                f'{ROWS}')
    print(json.dumps({'metric': 'serve_recovery_cold_seconds',
                      'value': round(median(cold_firsts), 4),
                      'unit': 's kill -> first token (cold re-submit)',
                      'drain_seconds': round(median(cold_drains), 4)}))
    hot = median(hot_firsts)
    cold = median(cold_firsts)
    print(json.dumps({
        'metric': 'serve_recovery_seconds',
        'value': round(hot, 4),
        'unit': f's kill -> first replayed token ({workload})'
                + ('' if ON_TPU else ' [CPU smoke]'),
        'cold_seconds': round(cold, 4),
        'hot_drain_seconds': round(median(hot_drains), 4),
        'cold_drain_seconds': round(median(cold_drains), 4),
    }))


if __name__ == '__main__':
    main()        # 'headline' arg tolerated: every section prints anyway
