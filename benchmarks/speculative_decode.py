"""Speculative vs plain greedy decode on one chip.

Speedup = f(draft agreement rate, draft/target cost ratio), so untrained
models measure only the overhead floor (~0.7x: every iteration pays
K+1 draft steps + 1 verify to emit one token). For a real number, target
and draft are first TRAINED on the same bigram corpus (SyntheticTokens)
until they agree on greedy continuations. Exactness caveat: output
equality with plain decode is bit-exact where matmul numerics are
window-length invariant — CPU float32 (pinned by tests/test_generate.py)
and TPU with jax_default_matmul_precision='highest' (verified). At the
TPU MXU's DEFAULT precision, f32 operands are truncated to bf16 with
tilings that depend on the query-window length, so the K+1-token verify
and 1-token decode can flip a near-tie argmax — 'exact match False' on a
v5e is the platform numeric, not an algorithmic bug (see
speculative_generate's docstring).
"""
import sys, time, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
import jax, jax.numpy as jnp, numpy as np
from functools import partial
from tpusystem.data import SyntheticTokens
from tpusystem.models import GPT2
from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                             flax_apply, generate, init_state,
                             speculative_generate)

VOCAB, SEQ, STEPS = 256, 64, 128

def train(module, steps=300):
    dataset = SyntheticTokens(samples=64 * 16, sequence_length=SEQ,
                              vocab_size=VOCAB)
    tokens = jnp.asarray(np.stack([dataset[i][0] for i in range(64)]))
    state = init_state(module, AdamW(lr=1e-3), tokens[:1])
    step = build_train_step(flax_apply(module), NextTokenLoss(),
                            AdamW(lr=1e-3), jit=False)
    @partial(jax.jit, donate_argnums=0)
    def run(state):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, tokens, tokens)[0], state)
    state = run(state)
    jax.tree.leaves(state.params)[0].block_until_ready()
    return state.params

target = GPT2(vocab_size=VOCAB, layers=8, dim=512, heads=8, max_seq=512,
              dropout=0.0, dtype='float32')  # f32: decode is overhead-bound
              # (equality with plain decode: see module docstring)
draft = GPT2(vocab_size=VOCAB, layers=1, dim=128, heads=2, max_seq=512,
             dropout=0.0, dtype='float32')
params = train(target)
draft_params = train(draft)

def timed(fn, tokens):
    np.asarray(fn())                         # compile
    start = time.perf_counter(); out = np.asarray(fn())
    return out, tokens / (time.perf_counter() - start)

# per-row cache cursors: each sequence advances by its own acceptance, so
# the speedup should survive batching instead of decaying to the batch-min
for batch in (1, 8):
    rows = [SyntheticTokens(samples=1, sequence_length=16, vocab_size=VOCAB,
                            seed=99 + i)[0][0] for i in range(batch)]
    prompt = jnp.asarray(np.stack(rows))
    plain, plain_tps = timed(
        lambda: generate(target, params, prompt, steps=STEPS), batch * STEPS)
    for K in (3, 5, 7):
        spec, spec_tps = timed(lambda: speculative_generate(
            target, params, prompt, steps=STEPS, draft_module=draft,
            draft_params=draft_params, speculate=K), batch * STEPS)
        # NOT guaranteed True on TPU at DEFAULT matmul precision: the MXU
        # truncates f32 operands to bf16 with window-length-dependent
        # tilings, so the K+1-token verify and 1-token decode can flip a
        # near-tie argmax (~1e-2 logit scatter measured on v5e). Exact
        # under jax_default_matmul_precision='highest' (verified) and on
        # CPU — see speculative_generate's docstring.
        exact = bool(np.array_equal(spec, plain))
        print(f'batch={batch} K={K}: plain {plain_tps:.0f} tok/s, '
              f'speculative {spec_tps:.0f} tok/s '
              f'({spec_tps/plain_tps:.2f}x), exact match {exact}')
