"""A/B: fused single-pass dq+dk+dv flash backward vs the split dq/dkv pair.

Times ``jax.grad`` of a flash-attention loss (fwd+bwd, the training shape)
at the headline and long-context shapes on the real chip. The fused kernel
recomputes scores and dprobs once per block instead of twice — 5 backward
matmuls instead of 7 — at the cost of a partial-dq HBM array when the KV
tiling has more than one step (``(kv_steps, bh, seq, d)``, summed after).

Run: ``python benchmarks/flash_backward_ab.py``
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.ops.pallas.flash import flash_attention

HEADS, HEAD_DIM = 12, 64
SHAPES = [  # (batch, seq) — headline then the long-context ladder
    (16, 1024),
    (4, 4096),
    (2, 8192),
    (1, 16384),
]
REPEATS = 20


def time_backward(batch: int, seq: int, backward: str) -> float:
    rng = np.random.default_rng(0)
    shape = (batch, seq, HEADS, HEAD_DIM)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
               for _ in range(3))

    def loss(q, k, v):
        out = flash_attention(q, k, v, causal=True, backward=backward)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def body(i, carry):
            dq, dk, dv = grad(q + carry[0] * 0, k, v)  # defeat hoisting
            return dq, dk, dv
        return jax.lax.fori_loop(0, REPEATS, body, (q, k, v))

    out = run(q, k, v)
    float(out[0].astype(jnp.float32).sum())  # force completion via relay
    start = time.perf_counter()
    out = run(q, k, v)
    float(out[0].astype(jnp.float32).sum())
    return (time.perf_counter() - start) / REPEATS


def main() -> None:
    for batch, seq in SHAPES:
        split = time_backward(batch, seq, 'split')
        fused = time_backward(batch, seq, 'fused')
        # charged attention matmul FLOPs (fwd 2 + bwd 4 of 2*S^2/2*D each,
        # causal halves the block area asymptotically — report raw ratio)
        print(f'b{batch} s{seq}: split {split * 1e3:8.3f} ms  '
              f'fused {fused * 1e3:8.3f} ms  speedup {split / fused:6.3f}x')


if __name__ == '__main__':
    main()
