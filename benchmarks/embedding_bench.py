"""Embedding lookup throughput: the fused Pallas row-gather vs the
``jnp.take`` fallback, swept over table size x batch (id count).

The recommender hot path is row movement, not FLOPs: a lookup streams
``ids * dim * itemsize`` bytes of table rows (plus the grad scatter-add
on the way back), so the metric is **looked-up rows per second** and the
interesting lever is whether the fused kernel's scalar-prefetched DMAs
beat XLA's gather at each shape. One JSON line per row (the
moe_dispatch convention); ``headline`` mode prints the single
``embedding_lookup_speedup`` row bench.py forwards (fwd+bwd at the
headline shape, fused over take).

On TPU the fused rows run the real kernels; off-TPU they run in
interpreter mode — numerics-true but orders of magnitude slower, so CPU
numbers are parity smoke, not performance (the speedup row says which).
REPS drop 50 -> 2 off-TPU for the same reason.
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.ops.pallas.embedding_lookup import embedding_lookup

ON_TPU = jax.default_backend() in ('tpu', 'axon')
REPS = 50 if ON_TPU else 2
TRIALS = 3
# off-TPU the fused rows run interpreter-mode kernels (numerics smoke,
# not performance) — the sequential grad scatter interprets one row at a
# time, so the smoke sweep shrinks to stay in seconds
SWEEP_TABLES = (65536, 1048576) if ON_TPU else (1024, 4096)
SWEEP_COUNTS = (4096, 32768) if ON_TPU else (256, 1024)
HEADLINE = (1048576, 128, 32768) if ON_TPU else (4096, 128, 1024)


def materialize(value) -> None:
    float(jnp.sum(jax.tree.leaves(value)[0].astype(jnp.float32)))


def _case(table_rows: int, dim: int, count: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    table = jnp.asarray(rng.standard_normal((table_rows, dim)), jnp.float32)
    # Zipf-flavored ids: the duplicate-heavy regime real click logs have
    pmf = 1.0 / np.arange(1, table_rows + 1) ** 1.3
    pmf /= pmf.sum()
    ids = jnp.asarray(rng.choice(table_rows, size=count, p=pmf), jnp.int32)
    weights = jnp.asarray(rng.uniform(0.5, 1.5, (count,)), jnp.float32)
    return table, ids, weights


def _timed(run, *operands) -> float:
    run(*operands)
    materialize(run(*operands))                      # warm + compile
    trials = []
    for _ in range(TRIALS):
        start = time.perf_counter()
        materialize(run(*operands))
        trials.append(time.perf_counter() - start)
    return sorted(trials)[len(trials) // 2]


def lookup_row(table_rows: int, dim: int, count: int, *,
               grad: bool = False) -> dict:
    table, ids, weights = _case(table_rows, dim, count)

    def chain(impl):
        def once(tab, wts):
            out = embedding_lookup(tab, ids, wts, impl=impl)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        # the carry perturbs the weights each iteration: a data
        # dependency defeats loop-invariant code motion (the
        # conv_ceiling.py lesson — a hoisted take path would time ~1
        # lookup amortized over REPS), at 1e-30 numeric cost
        if not grad:
            return jax.jit(lambda tab, wts: jax.lax.fori_loop(
                0, REPS,
                lambda i, acc: acc + once(tab, wts + acc * 1e-30),
                jnp.float32(0)))
        grad_fn = jax.grad(once)
        return jax.jit(lambda tab, wts: jax.lax.fori_loop(
            0, REPS,
            lambda i, acc: acc + jnp.sum(
                grad_fn(tab, wts + acc * 1e-30)[:1, :1]),
            jnp.float32(0)))

    take_s = _timed(chain('take'), table, weights)
    fused_s = _timed(chain('fused'), table, weights)
    to_rows = lambda seconds: count * REPS / seconds
    return {
        'metric': 'embedding_lookup',
        'phase': 'fwd+bwd' if grad else 'fwd',
        'table_rows': table_rows,
        'dim': dim,
        'batch_ids': count,
        'take_rows_per_s': round(to_rows(take_s)),
        'fused_rows_per_s': round(to_rows(fused_s)),
        'fused_speedup_vs_take': round(take_s / fused_s, 3),
        'backend': jax.default_backend(),
    }


def sweep() -> None:
    for table_rows in SWEEP_TABLES:
        for count in SWEEP_COUNTS:
            print(json.dumps(lookup_row(table_rows, 128, count)))
    print(json.dumps(lookup_row(*HEADLINE, grad=True)))


def headline() -> None:
    table_rows, dim, count = HEADLINE
    row = lookup_row(table_rows, dim, count, grad=True)
    print(json.dumps({
        'metric': 'embedding_lookup_speedup',
        'value': row['fused_speedup_vs_take'],
        'unit': (f'x (fused vs jnp.take, fwd+bwd, '
                 f'{table_rows} x {dim} table, {count} ids)'),
        'fused_rows_per_s': row['fused_rows_per_s'],
        'take_rows_per_s': row['take_rows_per_s'],
        'note': None if ON_TPU else ('CPU smoke: fused runs in interpreter '
                                     'mode — parity, not performance'),
    }))


if __name__ == '__main__':
    if 'headline' in sys.argv[1:]:
        headline()
    else:
        sweep()
        headline()
