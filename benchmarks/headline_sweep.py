"""Headline-recipe sweep: GPT-2 125M train MFU variants on one chip.

Same methodology as bench.py (donated fori_loop, materialized completion);
each variant prints one JSON line. Used to pick the recipe bench.py pins.
"""
import sys, time, json, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
from functools import partial

import jax, jax.numpy as jnp, numpy as np

from bench import peak_flops
from tpusystem.models import GPT2
from tpusystem.train import (AdamW, ChunkedNextTokenLoss, build_train_step,
                             flax_apply, init_state)


def variant(tag, batch=16, seq=1024, chunks=8, steps=60, **model_overrides):
    """One timed recipe; prints MFU + ms/step (+ tok/s for long context)."""
    config = dict(dropout=0.0, attention='flash', vocab_size=50304,
                  return_features=True)
    config.update(model_overrides)
    module = GPT2(**config)
    optimizer = AdamW(lr=3e-4, grad_clip=1.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 50257, (batch, seq)), jnp.int32)
    state = init_state(module, optimizer, tokens[:1, :8])
    params_count = sum(leaf.size for leaf in jax.tree.leaves(state.params))
    step = build_train_step(flax_apply(module),
                            ChunkedNextTokenLoss(chunks=chunks),
                            optimizer, jit=False)

    @partial(jax.jit, donate_argnums=0)
    def run(state, tokens):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, tokens, tokens)[0], state)

    state = run(state, tokens)
    float(jax.tree.leaves(state.params)[0].sum())
    start = time.perf_counter()
    state = run(state, tokens)
    float(jax.tree.leaves(state.params)[0].sum())
    elapsed = time.perf_counter() - start

    head_dim = module.dim // module.heads
    attention_flops = (12 * module.layers * module.heads * seq * seq
                       * head_dim * batch)
    step_flops = 6 * params_count * batch * seq + attention_flops
    mfu = step_flops * steps / elapsed / peak_flops(jax.devices()[0])
    print(json.dumps({'variant': tag, 'mfu': round(mfu, 4),
                      'ms_per_step': round(elapsed / steps * 1e3, 1),
                      'tok_per_s': round(batch * seq * steps / elapsed)}))
    return mfu


def safe(tag, **kw):
    try:
        variant(tag, **kw)
    except Exception as error:
        print(json.dumps({'variant': tag, 'error': str(error)[:120]}))


def flash_bwd(batch: int, seq: int, backward: str) -> float:
    """Seconds per fwd+bwd of a flash-attention loss with the given
    backward impl — the retired ``flash_backward_ab.py`` A/B, kept as a
    section here now that the fused single-pass backward is the default
    with working-set auto-routing (`ops/pallas/flash.py`)."""
    from tpusystem.ops.pallas.flash import flash_attention

    heads, head_dim, repeats = 12, 64, 20
    rng = np.random.default_rng(0)
    shape = (batch, seq, heads, head_dim)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
               for _ in range(3))

    def loss(q, k, v):
        out = flash_attention(q, k, v, causal=True, backward=backward)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def body(i, carry):
            dq, dk, dv = grad(q + carry[0] * 0, k, v)  # defeat hoisting
            return dq, dk, dv
        return jax.lax.fori_loop(0, repeats, body, (q, k, v))

    out = run(q, k, v)
    float(out[0].astype(jnp.float32).sum())  # force completion via relay
    start = time.perf_counter()
    out = run(q, k, v)
    float(out[0].astype(jnp.float32).sum())
    return (time.perf_counter() - start) / repeats


def flash_bwd_section():
    """Split-vs-fused flash backward at the headline + long-context
    shapes; one JSON line per shape."""
    for batch, seq in [(16, 1024), (4, 4096), (2, 8192), (1, 16384)]:
        try:
            split = flash_bwd(batch, seq, 'split')
            fused = flash_bwd(batch, seq, 'fused')
            print(json.dumps({
                'variant': f'flash_bwd b{batch} s{seq}',
                'split_ms': round(split * 1e3, 3),
                'fused_ms': round(fused * 1e3, 3),
                'fused_speedup': round(split / fused, 3)}))
        except Exception as error:
            print(json.dumps({'variant': f'flash_bwd b{batch} s{seq}',
                              'error': str(error)[:120]}))


def set_flash_tiles(block_q: int, block_kv: int):
    """Point the module-level kernel entry at a tile-pinned wrapper (the
    model families call ``flash_attention`` with defaults; ``attend``
    re-imports the module attribute per call, so swapping it here reaches
    every variant)."""
    from tpusystem.ops.pallas import flash
    original = getattr(flash, '_sweep_original', flash.flash_attention)
    flash._sweep_original = original

    def pinned(*args, **kwargs):
        kwargs.setdefault('block_q', block_q)
        kwargs.setdefault('block_kv', block_kv)
        return original(*args, **kwargs)
    flash.flash_attention = pinned


if __name__ == '__main__':
    if 'r5grid' in sys.argv[1:]:
        # round-5 re-sweep (VERDICT r4 #5): the round-2 recipe (b16,
        # 1024/1024, s90, c8) was tuned against the SPLIT backward; the
        # fused kernel shifts the compute/memory balance. Full grid under
        # backward='fused' (the default).
        for block_q, block_kv in [(1024, 1024), (512, 1024)]:
            set_flash_tiles(block_q, block_kv)
            for batch in (16, 24, 32):
                for steps in (90, 120):
                    for chunks in (8, 4):
                        safe(f'b{batch} t{block_q}/{block_kv} '
                             f's{steps} c{chunks}',
                             batch=batch, steps=steps, chunks=chunks)
    elif 'flash_bwd' in sys.argv[1:]:
        # the retired flash_backward_ab.py A/B: fused single-pass
        # dq+dk+dv backward vs the split dq/dkv pair, headline +
        # long-context shapes on the real chip
        flash_bwd_section()
    elif 'long' in sys.argv[1:]:
        # long-context ladder (BASELINE.md): 125M body, remat + fused loss
        # + flash, constant 16k tokens per step
        for batch, seq in [(4, 4096), (2, 8192), (1, 16384)]:
            safe(f'long b{batch} s{seq}', batch=batch, seq=seq, steps=30,
                 max_seq=seq, remat=True)
    else:
        safe('baseline b16 c8 s60')
        safe('repeat   b16 c8 s60')
        safe('batch 24', batch=24)
        safe('chunks 4', chunks=4)
        safe('steps 90', steps=90)
        # scan_layers: the relay's AOT compile helper 500s on the
        # scan+pallas composition (runtime path works on CPU; compile-time
        # win measured in compile_time.py) — keep it out of the default
        # sweep
        safe('steps 120', steps=120)
