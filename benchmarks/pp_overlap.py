"""Pipeline p2p hiding: skewed-overlap GPipe ticks vs the classic tick.

The classic GPipe tick sends a stage's output AFTER the compute that
produced it — inside a sequential ``lax.scan``, that ``ppermute`` sits on
the critical path between every pair of ticks. The ``pp='overlap'`` arm
of the unified scheduler (`tpusystem/parallel/schedule.py`) skews the
schedule one tick per hop so each send is issued UNDER the next
microbatch's stage compute (`tpusystem/parallel/pipeline.py`;
`collectives.pp_hop` carries the custom_vjp so the backward's reversed
sends hide the same way). This benchmark times a stacked-matmul pipe
fwd+bwd both ways at each shape:

  pipe[classic]        post-compute sends (pp='gspmd', the default tick)
  pipe[overlap cN]     skewed double-buffered ticks, N ppermute chunks
                       per hop

All rows are fwd+bwd with the conv_ceiling data-chained discipline (the
loss is a sum of squares, every gradient folds back into the carried
inputs — nothing hoists or DCEs). ``python benchmarks/pp_overlap.py``
prints the table + summary; ``... headline`` prints the single JSON line
`bench.py` forwards (`pp_overlap_speedup_vs_gspmd`).

Hardware: uses the real accelerator mesh when >= 2 devices are present
(real numbers); otherwise re-execs itself onto an 8-device virtual CPU
mesh at smoke shapes — same code paths, scheduler-free numbers that only
smoke-test the sweep (XLA:CPU has no latency-hiding scheduler, and the
skewed schedule's extra fill ticks make the virtual ratio < 1; see
BASELINE.md "pp/moe overlap protocol").
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import os
import time

if os.environ.get('_PP_OVERLAP_VIRTUAL'):
    from tpusystem.parallel import force_host_platform
    force_host_platform(8)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bench import materialize as _materialize


def _ensure_devices():
    devices = jax.devices()
    if devices[0].platform != 'cpu' and len(devices) >= 2:
        return devices, False
    if devices[0].platform == 'cpu' and len(devices) >= 4:
        return devices, True
    env = dict(os.environ)
    env['_PP_OVERLAP_VIRTUAL'] = '1'
    flag = '--xla_force_host_platform_device_count'
    if flag not in env.get('XLA_FLAGS', ''):
        env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') + f' {flag}=8').strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


DEVICES, VIRTUAL = _ensure_devices()
STAGES = max(size for size in (2, 4) if size <= len(DEVICES))
# smoke shapes on the virtual mesh; real shapes on chips
LAYERS, BATCH, DIM, MICRO, REPS = ((STAGES * 2, 8, 256, 4, 5) if VIRTUAL
                                   else (STAGES * 2, 8, 4096, 8, 20))
CHUNK_COUNTS = (1, 2)


def time_fwd_bwd(fn, *args) -> float:
    """Seconds per fwd+bwd over REPS chained iterations (the
    benchmarks/README.md methodology)."""
    def loss_fn(*a):
        out = fn(*a)
        return jnp.sum(jnp.square(out.astype(jnp.float32))) * 1e-9

    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(len(args))))

    def chain(tree):
        total = jnp.float32(0)
        for leaf in jax.tree.leaves(tree):
            total = total + leaf.reshape(-1)[0].astype(jnp.float32)
        return total

    def body(_, carry):
        loss, grads = vg(*carry)
        feedback = (loss + chain(grads)) * 1e-7
        return tuple(a + feedback.astype(a.dtype) for a in carry)

    run = jax.jit(lambda *a: lax.fori_loop(0, REPS, body, a))
    out = run(*args)
    _materialize(out)
    t0 = time.perf_counter()
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


def _build():
    from tpusystem.parallel import (MeshSpec, OverlapSchedule,
                                    pipeline_apply, pp_plan)

    mesh = MeshSpec(stage=STAGES, data=len(DEVICES) // STAGES).build(DEVICES)
    rng = np.random.default_rng(0)
    dtype = jnp.float32 if VIRTUAL else jnp.bfloat16
    weights = jnp.asarray(
        rng.normal(size=(LAYERS, DIM, DIM)) * (1.0 / np.sqrt(DIM)), dtype)
    inputs = jnp.asarray(rng.normal(size=(BATCH * MICRO
                                          * mesh.shape['data'], DIM)) * 0.1,
                         dtype)
    block_fn = lambda lp, x: jnp.tanh(x @ lp)
    micro_rows = inputs.shape[0] // mesh.shape['data'] // MICRO

    cases = {}
    cases['pipe[classic]'] = (
        lambda w, x: pipeline_apply(block_fn, w, x, mesh, microbatches=MICRO,
                                    remat=False),
        (weights, inputs), 'post-compute sends on the tick critical path')
    for chunks in CHUNK_COUNTS:
        plan = pp_plan(micro_rows, STAGES, chunks=chunks)
        if plan.path != 'overlap':
            continue
        schedule = OverlapSchedule(pp='overlap', chunks=chunks)
        cases[f'pipe[overlap c{chunks}]'] = (
            lambda w, x, schedule=schedule: pipeline_apply(
                block_fn, w, x, mesh, microbatches=MICRO, remat=False,
                schedule=schedule),
            (weights, inputs),
            'skewed ticks: sends ride under the next microbatch compute')
    return cases


def sweep() -> dict[str, float]:
    times = {}
    for tag, (fn, args, note) in _build().items():
        seconds = time_fwd_bwd(fn, *args)
        times[tag] = seconds
        print(json.dumps({'phase': tag, 'us': round(seconds * 1e6, 1),
                          'note': note}))
    overlaps = {tag: t for tag, t in times.items() if 'overlap' in tag}
    best_tag, best = min(overlaps.items(), key=lambda pair: pair[1])
    print(json.dumps({'summary': {
        'mesh': f"{DEVICES[0].platform} stage={STAGES}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'layers': LAYERS, 'batch': BATCH, 'dim': DIM, 'microbatches': MICRO,
        'best_overlap': best_tag,
        'overlap_vs_classic': round(times['pipe[classic]'] / best, 3),
    }}))
    return times


def headline() -> None:
    """The single JSON line bench.py forwards as its pp_overlap row."""
    times = {tag: time_fwd_bwd(fn, *args)
             for tag, (fn, args, _) in _build().items()}
    overlaps = {tag: t for tag, t in times.items() if 'overlap' in tag}
    best_tag, best = min(overlaps.items(), key=lambda pair: pair[1])
    print(json.dumps({
        'metric': 'pp_overlap_speedup_vs_gspmd',
        'value': round(times['pipe[classic]'] / best, 4),
        'unit': 'x',
        'mesh': f"{DEVICES[0].platform} stage={STAGES}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'chunks': int(best_tag.split('c')[-1].rstrip(']')),
        'classic_us': round(times['pipe[classic]'] * 1e6, 1),
        'overlap_us': round(best * 1e6, 1),
    }))


if __name__ == '__main__':
    if 'headline' in sys.argv[1:]:
        headline()
    else:
        sweep()
