"""Router failover MTTR: kill the active Router → first completed token.

The crash-recoverable Router's promise (``tpusystem/serve/fleet.py`` +
the journaled state of ``tpusystem/serve/failover.py``) measured: a
three-replica fleet is serving a mixed workload when the active Router
is abandoned mid-stream (the in-process stand-in for SIGKILL — the
replicas and the memstore plane outlive it, exactly what a real router
crash leaves behind), and a warm standby takes over. Recovery is timed
from the kill to the **first completed token under the standby** two
ways:

1. ``hot``  — the router journal is recovered from the plane: seated
             rows re-attach and keep streaming, queued rows re-place,
             settled results survive;
2. ``cold`` — no journal (the plane lost it): the health sweep alone
             rebuilds the tables from the replicas' own request
             journals and results — what takeover costs when the
             journal cadence lost the race.

Both arms fence the lease term first (the split-brain guard is part of
the measured path) and both drain token-exact vs an uninterrupted
fleet (asserted every trial — greedy decode is deterministic).

Every row is one machine-readable JSON line (the ``decode_roofline.py``
convention); the LAST line is the ``router_failover_seconds`` headline
``bench.py`` forwards (value = hot takeover-to-first-completion
seconds, with the cold arm alongside). CPU numbers are smoke; the TPU
protocol rides the same script (BASELINE.md "router failover protocol").

Run: ``python benchmarks/serve_failover.py [headline]``.
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.checkpoint.memstore import MemStore
from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.serve import (Engine, ReplicaHandle, Request, Router,
                             RouterJournal, RouterLease, Scheduler,
                             ServingReplica)

TRIALS = 3
REPLICAS = 3
ROWS = 2
KILL_TICK = 4
ON_TPU = jax.default_backend() in ('tpu', 'axon')


def recipe():
    """Model + workload (the ``serve_recovery.py`` sizing discipline)."""
    if ON_TPU:
        module = GPT2(dropout=0.0, vocab_size=50304, max_seq=512)
        lengths, vocab = (16, 32, 64, 96), 50257
        budgets = (24, 24, 24, 96) * 2
    else:
        module = gpt2_tiny(dtype='float32', layers=4, dim=256, heads=8,
                           vocab_size=1024, max_seq=256)
        lengths, vocab = (4, 8, 16, 24), 1024
        budgets = (12, 12, 12, 48) * 2
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, (lengths[i % len(lengths)],))
               .astype(np.int32).tolist() for i in range(len(budgets))]
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray([prompts[0]], jnp.int32))['params']
    return module, params, prompts, list(budgets)


def build_fleet(module, params, plane, *, holder='router'):
    """Three journaled replicas under a leased, journaled Router whose
    authoritative state replicates to ``plane`` every tick."""
    handles = []
    for index in range(REPLICAS):
        def build():
            return Scheduler(Engine(module, params, rows=ROWS,
                                    block_size=16 if ON_TPU else 8))
        handles.append(ReplicaHandle(ServingReplica(
            build, identity=f'rep{index}', client=MemStore(), cadence=1)))
    lease = RouterLease(client=plane, holder=holder)
    router = Router(handles, journal=RouterJournal(client=plane, cadence=1),
                    lease=lease)
    lease.acquire()
    return router


def run_to_kill(module, params, prompts, budgets, plane):
    """Serve up to KILL_TICK under the incumbent, then abandon it (the
    kill). Returns the fleet's surviving pieces: the replica handles
    and the results already settled before the kill."""
    router = build_fleet(module, params, plane)
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        router.submit(Request(f'r{index}', prompt, budget))
    for _ in range(KILL_TICK):
        router.step()
    return router.handles, dict(router.results)


def takeover(module, params, handles, plane, journal_plane, reference):
    """Time kill -> first completed token under the standby, for one
    arm: ``journal_plane`` holding the router journal (hot) or an empty
    one (cold sweep). The lease fence and the recovery replay are both
    inside the timed window — this IS the MTTR the client sees."""
    start = time.perf_counter()
    lease = RouterLease(client=plane, holder='standby')
    standby = Router(handles, journal=RouterJournal(client=journal_plane,
                                                    cadence=1), lease=lease)
    lease.acquire()                 # fence the old term: split-brain guard
    report = standby.recover((journal_plane,))
    first_completion = None
    while not standby.idle:
        tick = standby.step()
        if first_completion is None and tick.completed:
            first_completion = time.perf_counter() - start
    drained = time.perf_counter() - start
    if first_completion is None:    # everything settled pre-kill/recover
        first_completion = drained
    for rid, completion in standby.results.items():
        expected = reference[rid].tokens
        assert completion.tokens == expected, (
            f'{rid} diverged across the takeover: {completion.tokens} vs '
            f'{expected}')
    return first_completion, drained, report['source']


def main() -> None:
    module, params, prompts, budgets = recipe()

    # the uninterrupted reference: the same fleet, never killed
    router = build_fleet(module, params, MemStore())
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        router.submit(Request(f'r{index}', prompt, budget))
    reference = router.run_until_idle()

    hot_firsts, hot_drains = [], []
    cold_firsts, cold_drains = [], []
    for _ in range(TRIALS):
        plane = MemStore()
        handles, _pre = run_to_kill(module, params, prompts, budgets, plane)
        first, drained, source = takeover(
            module, params, handles, plane, plane, reference)
        assert source == 'journal', f'hot arm recovered via {source!r}'
        hot_firsts.append(first)
        hot_drains.append(drained)

        plane = MemStore()
        handles, _pre = run_to_kill(module, params, prompts, budgets, plane)
        first, drained, source = takeover(
            module, params, handles, plane, MemStore(), reference)
        assert source == 'sweep', f'cold arm recovered via {source!r}'
        cold_firsts.append(first)
        cold_drains.append(drained)

    median = lambda times: sorted(times)[len(times) // 2]
    workload = (f'{len(prompts)} reqs, {REPLICAS} replicas, router killed '
                f'at tick {KILL_TICK}')
    print(json.dumps({'metric': 'router_failover_cold_seconds',
                      'value': round(median(cold_firsts), 4),
                      'unit': 's kill -> first completion (cold sweep)',
                      'drain_seconds': round(median(cold_drains), 4)}))
    print(json.dumps({
        'metric': 'router_failover_seconds',
        'value': round(median(hot_firsts), 4),
        'unit': f's kill -> first completion under the standby ({workload})'
                + ('' if ON_TPU else ' [CPU smoke]'),
        'cold_seconds': round(median(cold_firsts), 4),
        'hot_drain_seconds': round(median(hot_drains), 4),
        'cold_drain_seconds': round(median(cold_drains), 4),
    }))


if __name__ == '__main__':
    main()        # 'headline' arg tolerated: every section prints anyway
