"""Decode roofline: what does one greedy-decode token-step *have* to cost?

BASELINE's decode row (GPT-2 125M, batch 8, prefill 128, decode 128) is
2310 tok/s = 3.46 ms per token-step on 1x v5e. This file writes the
weight-streaming roofline next to it and decomposes the gap:

1. ``bandwidth``    — big-copy effective HBM bandwidth of the chip
2. ``stream_f32``   — the exact decode matmul chain (12 layers qkv/out/
                      fc/proj + LM head) with float32 master weights, the
                      layout ``generate()`` historically streamed
3. ``stream_bf16``  — identical chain with pre-cast bfloat16 weights
                      (identical matmul numerics — the bf16 cast happens
                      per-use anyway; only the HBM bytes halve)
4. ``stream_int8``/``stream_fp8`` — identical chain with per-channel
                      symmetric quantized weights (`ops/precision.py`):
                      the narrow values are the streamed operand, the f32
                      scale multiplies the accumulator — weight bytes
                      halve AGAIN vs bf16
5. ``fused_*``      — the same chain through the Pallas fused decode
                      kernels (`ops/pallas/decode_matmul.py`): activation
                      VMEM-resident, weights streamed tile-by-tile,
                      int8 tiles dequantized in-kernel, fc→gelu→proj in
                      one kernel
6. ``generate[*]``  — the real ``generate()`` under every streaming mode
                      and the fused decode impl

Roofline: 125M params x 4 B (f32) = ~500 MB/step → ~0.61 ms at the v5e's
~819 GB/s; bf16 halves it to ~0.31 ms, int8/fp8 to ~0.15 ms. The
measured chain vs the measured copy bandwidth separates "medium-matmul
streaming is below copy bandwidth" (platform) from "the decode loop adds
overhead on top" (framework).

Every row is one machine-readable JSON line (the `moe_dispatch.py`
convention). ``weight_stream_bytes`` is the per-step streamed weight
bytes (the roofline quantity); quantized rows list their per-channel
scale bytes separately (``scale_stream_bytes`` — ~0.5% overhead, also
streamed per step) and ``bytes_vs_bf16`` is the weight-stream reduction
(exactly 2x for int8/fp8 vs bf16).

Run: ``python benchmarks/decode_roofline.py [chain|fused|generate|scaling]``
(no arg = all sections; on CPU the fused section runs the kernels in
interpret mode — parity smoke, not a timing).
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bench import materialize as _materialize
from tpusystem.ops.precision import (QuantizedLeaf, fp8_unsupported_reason,
                                     quantize_leaf)

BATCH, DIM, LAYERS, VOCAB = 8, 768, 12, 50304
# Off-TPU the chain runs at emulated-bf16 CPU speed — enough reps for a
# stable median would take tens of minutes, and the numbers are smoke
# anyway (the tp_overlap.py VIRTUAL discipline). TPU keeps the real count.
REPS = 200 if jax.default_backend() in ('tpu', 'axon') else 10


def _time(run, *args) -> float:
    out = run(*args)
    _materialize(out)
    t0 = time.perf_counter()
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


# v5e paper HBM bandwidth. Measured probes mislead here: a fori_loop of
# per-slice reductions reports 20 GB/s (loop overhead) and one giant
# fused multiply-reduce reports 33 GB/s (reduction lowering), while the
# decode matmul chain itself sustains ~280 GB/s — the matmul chain IS
# the honest streaming measurement; the paper number anchors the floor.
PAPER_HBM_GBS = 819.0

CHAIN_SHAPES = [(DIM, 3 * DIM), (DIM, DIM), (DIM, 4 * DIM), (4 * DIM, DIM)]


def _chain_weights(mode: str):
    """The exact decode chain's weights in one streaming mode:
    ``'f32'``/``'bf16'`` plain, ``'int8'``/``'fp8'`` per-channel
    quantized. Returns (layers, head, weight_bytes, scale_bytes)."""
    rng = np.random.default_rng(0)

    def make(shape):
        wide = jnp.asarray(rng.normal(size=shape) * 0.02, jnp.float32)
        if mode == 'f32':
            return wide
        if mode == 'bf16':
            return wide.astype(jnp.bfloat16)
        return quantize_leaf(wide, mode)

    layers = [tuple(make(shape) for shape in CHAIN_SHAPES)
              for _ in range(LAYERS)]
    head = make((DIM, VOCAB))
    flat = [w for ws in layers for w in ws] + [head]
    weight_bytes = sum(w.values.nbytes if isinstance(w, QuantizedLeaf)
                       else w.nbytes for w in flat)
    scale_bytes = sum(w.scales.nbytes for w in flat
                      if isinstance(w, QuantizedLeaf))
    return layers, head, weight_bytes, scale_bytes


def _mm(x, w):
    """One chain matmul in the mode's streamed form: plain weights cast
    to bf16 per use (as the model's Dense layers do); quantized weights
    contract their narrow values and scale the result — qdot's math,
    chain-dtype flavored."""
    if isinstance(w, QuantizedLeaf):
        return ((x @ w.values.astype(jnp.bfloat16))
                * w.scales).astype(jnp.bfloat16)
    return x @ w.astype(jnp.bfloat16)


def stream_chain(weights, fused: bool = False) -> float:
    """ms per step of the exact decode matmul chain over prebuilt
    ``_chain_weights`` output; ``fused=True`` routes the per-layer sweep
    through the Pallas decode kernels instead of plain einsums. Every
    weight — the LM head included — is threaded through the jitted
    runner's ARGUMENTS: a closed-over array is a compile-time constant
    XLA would happily cast/dequantize once outside the scan, un-streaming
    the very bytes this file measures."""
    layers, head, _, _ = weights
    rng = np.random.default_rng(0)
    x0 = jnp.asarray(rng.normal(size=(BATCH, DIM)), jnp.bfloat16)
    if fused:
        from tpusystem.ops.pallas.decode_matmul import (decode_ffn,
                                                        decode_matmul)
        zero_hidden = jnp.zeros((4 * DIM,), jnp.float32)
        zero_dim = jnp.zeros((DIM,), jnp.float32)

        def sweep(x, qkv, out, fc, proj):
            h = decode_matmul(x, qkv)
            x = x + decode_matmul(h[:, :DIM], out)
            return x + decode_ffn(x, fc, zero_hidden, proj, zero_dim)

        def logits_of(x, head):
            return decode_matmul(x, head)
    else:
        def sweep(x, qkv, out, fc, proj):
            h = _mm(x, qkv)
            x = x + _mm(h[:, :DIM], out)
            return x + _mm(jax.nn.gelu(_mm(x, fc)), proj)

        def logits_of(x, head):
            return _mm(x, head)

    @jax.jit
    def run(x0, layers, head):
        def step(carry, _):
            x = carry
            for qkv, out, fc, proj in layers:
                x = sweep(x, qkv, out, fc, proj)
            logits = logits_of(x, head)
            # argmax feedback: the next step depends on this one (no
            # hoisting), like real greedy decode
            x = x0 + (jnp.argmax(logits, -1)[:, None] % 7).astype(
                jnp.bfloat16) * 1e-3
            return x, logits[0, 0]
        _, ys = jax.lax.scan(step, x0, None, length=REPS)
        return ys

    return _time(run, x0, tuple(layers), head) * 1e3


def chain_row(mode: str, bf16_bytes: int | None, fused: bool = False) -> int:
    """Print one chain row; returns the row's weight-stream bytes."""
    weights = _chain_weights(mode)       # built ONCE per row (~0.5 GB)
    _, _, weight_bytes, scale_bytes = weights
    ms = stream_chain(weights, fused=fused)
    total = weight_bytes + scale_bytes
    floor = total / (PAPER_HBM_GBS * 1e9) * 1e3
    row = {'ms_per_step': round(ms, 3),
           'weight_stream_bytes': weight_bytes,
           'weight_mb': round(total / 2**20),
           'effective_gbs': round(total / ms * 1e-6, 1),
           'paper_bw_floor_ms': round(floor, 3),
           'vs_floor': round(ms / floor, 2)}
    if scale_bytes:
        row['scale_stream_bytes'] = scale_bytes
    if bf16_bytes is not None:
        row['bytes_vs_bf16'] = round(bf16_bytes / weight_bytes, 2)
    tag = f'fused_{mode}' if fused else f'stream_{mode}'
    print(json.dumps({tag: row}))
    return weight_bytes


def chain_section() -> None:
    bf16_bytes = None
    for mode in ('f32', 'bf16', 'int8', 'fp8'):
        if mode == 'fp8':
            reason = fp8_unsupported_reason()
            if reason is not None:
                print(json.dumps({'stream_fp8': {'skipped': reason}}))
                continue
        bytes_now = chain_row(mode, bf16_bytes)
        if mode == 'bf16':
            bf16_bytes = bytes_now


def fused_section() -> None:
    """The chain through the Pallas fused decode kernels. On TPU this is
    the streamed-tile timing; on CPU the kernels run in interpret mode —
    a parity smoke whose ms column is meaningless."""
    # bf16 chain bytes are shape arithmetic — no need to build the arrays
    bf16_bytes = 2 * (LAYERS * sum(rows * cols for rows, cols in CHAIN_SHAPES)
                      + DIM * VOCAB)
    for mode in ('bf16', 'int8'):
        chain_row(mode, bf16_bytes if mode != 'bf16' else None, fused=True)


def measured_generate(stream_dtype: str, decode_impl: str = 'auto') -> None:
    """tok/s of the real generate() at the BASELINE row's config."""
    from tpusystem.models import GPT2
    from tpusystem.train.generate import generate, streamed_bytes

    module = GPT2(dropout=0.0, vocab_size=VOCAB, max_seq=512)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (BATCH, 128)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt[:1, :8])['params']

    run = partial(generate, module, params, prompt, steps=128,
                  stream_dtype=stream_dtype, decode_impl=decode_impl)
    np.asarray(run())
    t0 = time.perf_counter()
    np.asarray(run())
    elapsed = time.perf_counter() - t0
    tok = BATCH * 128 / elapsed
    tag = (f'generate[{stream_dtype}]' if decode_impl == 'auto'
           else f'generate[{stream_dtype},{decode_impl}]')
    print(json.dumps({tag: {
        'tok_per_s': round(tok),
        'ms_per_token_step': round(BATCH * 1e3 / tok, 3),
        'stream_bytes_per_step': streamed_bytes(module, params,
                                                stream_dtype)}}))


def generate_section() -> None:
    modes = ['float32', 'auto', 'bfloat16', 'int8']
    if fp8_unsupported_reason() is None:
        modes.append('fp8')
    for mode in modes:
        measured_generate(mode)
    # the fused decode impl (Pallas chain inside the compiled loop) —
    # forced, so CPU runs exercise interpret-mode parity too
    measured_generate('int8', decode_impl='fused')


def scaling() -> None:
    """tok/s vs cache capacity (bucketed reads) and batch (weight-stream
    amortization) — the two levers the roofline exposes."""
    from tpusystem.models import GPT2
    from tpusystem.train.generate import generate

    for batch, max_seq in [(8, 256), (8, 512), (8, 1024), (32, 512),
                           (64, 512)]:
        module = GPT2(dropout=0.0, vocab_size=VOCAB, max_seq=max_seq)
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, VOCAB, (batch, 128)), jnp.int32)
        params = module.init(jax.random.PRNGKey(0), prompt[:1, :8])['params']
        out = generate(module, params, prompt, steps=128)
        np.asarray(out)
        t0 = time.perf_counter()
        out = generate(module, params, prompt, steps=128)
        np.asarray(out)
        elapsed = time.perf_counter() - t0
        print(json.dumps({'batch': batch, 'max_seq': max_seq,
                          'tok_per_s': round(batch * 128 / elapsed),
                          'ms_per_step': round(elapsed / 128 * 1e3, 3)}))


def main() -> None:
    sections = {'chain': chain_section, 'fused': fused_section,
                'generate': generate_section, 'scaling': scaling}
    picked = [arg for arg in sys.argv[1:] if arg in sections]
    for name, section in sections.items():
        if not picked or name in picked:
            section()


if __name__ == '__main__':
    main()
