"""Decode roofline: what does one greedy-decode token-step *have* to cost?

BASELINE's decode row (GPT-2 125M, batch 8, prefill 128, decode 128) is
2310 tok/s = 3.46 ms per token-step on 1x v5e. This file writes the
weight-streaming roofline next to it and decomposes the gap:

1. ``bandwidth``   — big-copy effective HBM bandwidth of the chip
2. ``stream_f32``  — the exact decode matmul chain (12 layers qkv/out/
                     fc/proj + LM head) with float32 master weights, the
                     layout ``generate()`` historically streamed
3. ``stream_bf16`` — identical chain with pre-cast bfloat16 weights
                     (identical matmul numerics — the bf16 cast happens
                     per-use anyway; only the HBM bytes halve)
4. ``generate``    — the real ``generate()`` under both streaming modes

Roofline: 125M params x 4 B (f32) = ~500 MB/step → ~0.61 ms at the v5e's
~819 GB/s; bf16 halves it to ~0.31 ms. The measured chain vs the
measured copy bandwidth separates "medium-matmul streaming is below
copy bandwidth" (platform) from "the decode loop adds overhead on top"
(framework).

Run: ``python benchmarks/decode_roofline.py``
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bench import materialize as _materialize

BATCH, DIM, LAYERS, VOCAB = 8, 768, 12, 50304
REPS = 200


def _time(run, *args) -> float:
    out = run(*args)
    _materialize(out)
    t0 = time.perf_counter()
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


# v5e paper HBM bandwidth. Measured probes mislead here: a fori_loop of
# per-slice reductions reports 20 GB/s (loop overhead) and one giant
# fused multiply-reduce reports 33 GB/s (reduction lowering), while the
# decode matmul chain itself sustains ~280 GB/s — the matmul chain IS
# the honest streaming measurement; the paper number anchors the floor.
PAPER_HBM_GBS = 819.0


def stream_chain(dtype) -> tuple[float, int]:
    """ms per step of the exact decode matmul chain, weights in ``dtype``
    (cast to bf16 per use, as the model's Dense layers do)."""
    rng = np.random.default_rng(0)
    layers = []
    for _ in range(LAYERS):
        layers.append(tuple(
            jnp.asarray(rng.normal(size=shape) * 0.02, dtype)
            for shape in [(DIM, 3 * DIM), (DIM, DIM), (DIM, 4 * DIM),
                          (4 * DIM, DIM)]))
    head = jnp.asarray(rng.normal(size=(DIM, VOCAB)) * 0.02, dtype)
    x0 = jnp.asarray(rng.normal(size=(BATCH, DIM)), jnp.bfloat16)
    nbytes = (sum(w.nbytes for ws in layers for w in ws) + head.nbytes)

    @jax.jit
    def run(x0, layers, head):
        def step(carry, _):
            x = carry
            for qkv, out, fc, proj in layers:
                h = x @ qkv.astype(jnp.bfloat16)
                x = x + h[:, :DIM] @ out.astype(jnp.bfloat16)
                g = jax.nn.gelu(x @ fc.astype(jnp.bfloat16))
                x = x + g @ proj.astype(jnp.bfloat16)
            logits = x @ head.astype(jnp.bfloat16)
            # argmax feedback: the next step depends on this one (no
            # hoisting), like real greedy decode
            x = x0 + (jnp.argmax(logits, -1)[:, None] % 7).astype(jnp.bfloat16) * 1e-3
            return x, logits[0, 0]
        _, ys = jax.lax.scan(step, x0, None, length=REPS)
        return ys

    return _time(run, x0, tuple(layers), head) * 1e3, nbytes


def measured_generate(stream_dtype: str) -> float:
    """tok/s of the real generate() at the BASELINE row's config."""
    from tpusystem.models import GPT2
    from tpusystem.train.generate import generate

    module = GPT2(dropout=0.0, vocab_size=VOCAB, max_seq=512)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (BATCH, 128)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt[:1, :8])['params']

    out = generate(module, params, prompt, steps=128,
                   stream_dtype=stream_dtype)
    np.asarray(out)
    t0 = time.perf_counter()
    out = generate(module, params, prompt, steps=128,
                   stream_dtype=stream_dtype)
    np.asarray(out)
    elapsed = time.perf_counter() - t0
    return BATCH * 128 / elapsed


def scaling() -> None:
    """tok/s vs cache capacity (bucketed reads) and batch (weight-stream
    amortization) — the two levers the roofline exposes."""
    from tpusystem.models import GPT2
    from tpusystem.train.generate import generate

    for batch, max_seq in [(8, 256), (8, 512), (8, 1024), (32, 512),
                           (64, 512)]:
        module = GPT2(dropout=0.0, vocab_size=VOCAB, max_seq=max_seq)
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, VOCAB, (batch, 128)), jnp.int32)
        params = module.init(jax.random.PRNGKey(0), prompt[:1, :8])['params']
        out = generate(module, params, prompt, steps=128)
        np.asarray(out)
        t0 = time.perf_counter()
        out = generate(module, params, prompt, steps=128)
        np.asarray(out)
        elapsed = time.perf_counter() - t0
        print(json.dumps({'batch': batch, 'max_seq': max_seq,
                          'tok_per_s': round(batch * 128 / elapsed),
                          'ms_per_step': round(elapsed / 128 * 1e3, 3)}))


def main() -> None:
    for dtype, tag in [(jnp.float32, 'f32'), (jnp.bfloat16, 'bf16')]:
        ms, nbytes = stream_chain(dtype)
        floor = nbytes / (PAPER_HBM_GBS * 1e9) * 1e3
        print(json.dumps({
            f'stream_{tag}': {'ms_per_step': round(ms, 3),
                              'weight_mb': round(nbytes / 2**20),
                              'effective_gbs': round(nbytes / ms * 1e-6, 1),
                              'paper_bw_floor_ms': round(floor, 3),
                              'vs_floor': round(ms / floor, 2)}}))
    for mode in ('float32', 'auto'):
        tok = measured_generate(mode)
        print(json.dumps({f'generate[{mode}]': {
            'tok_per_s': round(tok),
            'ms_per_token_step': round(BATCH * 1e3 / tok, 3)}}))
    scaling()


if __name__ == '__main__':
    main()
