"""FSDP param-prefetch / grad-scatter hiding: the three-way sweep.

GSPMD lowers a ZeRO-3 layer to a *monolithic* parameter all-gather on
the critical path of every block and a *monolithic* gradient
reduce-scatter on its backward. The unified overlap scheduler
(`tpusystem/parallel/schedule.py`) decomposes both into the ring idiom
the TP collectives proved (`benchmarks/tp_overlap.py`). This benchmark
times the FSDP-sharded FFN's phases three ways at each shape — the
tp_overlap-style per-phase table:

  wg_mm[gspmd]       partitioner-inserted weight all-gather + matmul
  wg_mm[one-shot]    manual shard_map: lax.all_gather the kernel, matmul
  wg_mm[overlap cN]  decomposed ring gather (schedule.prefetched), N
                     ppermute chunks per hop
  ffn[gspmd]         the whole up -> gelu -> down block, GSPMD collectives
  ffn[one-shot]      manual monolithic kernel gathers inside shard_map
  ffn[overlap cN]    scheduled_ffn under OverlapSchedule(fsdp='prefetch')
  composed[...]      fsdp x model mesh: TP rings AND FSDP prefetch under
                     ONE schedule vs the all-GSPMD baseline (>= 4 devices)

All rows are fwd+bwd with the conv_ceiling data-chained discipline (the
loss is a sum of squares, every gradient folds back into the carried
inputs — nothing hoists or DCEs), so the backward's grad reduce-scatter
is timed too. `python benchmarks/fsdp_overlap.py` prints the table +
summary; `... headline` prints the single JSON line `bench.py` forwards
(`fsdp_overlap_speedup_vs_gspmd`).

Hardware: uses the real accelerator mesh when >= 2 devices are present
(real numbers); otherwise re-execs itself onto an 8-device virtual CPU
mesh at smoke shapes — same code paths, scheduler-free numbers that only
smoke-test the sweep (BASELINE.md "tp_overlap protocol" applies
verbatim: XLA:CPU has no latency-hiding scheduler).
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import functools
import json
import os
import time

if os.environ.get('_FSDP_OVERLAP_VIRTUAL'):
    from tpusystem.parallel import force_host_platform
    force_host_platform(8)

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bench import materialize as _materialize


def _ensure_devices():
    """Real accelerator mesh when it exists; else re-exec onto the
    virtual CPU mesh (force_host_platform must precede backend init, so
    a fresh process is the only clean path)."""
    devices = jax.devices()
    if devices[0].platform != 'cpu' and len(devices) >= 2:
        return devices, False
    if devices[0].platform == 'cpu' and len(devices) >= 4:
        return devices, True
    env = dict(os.environ)
    env['_FSDP_OVERLAP_VIRTUAL'] = '1'
    flag = '--xla_force_host_platform_device_count'
    if flag not in env.get('XLA_FLAGS', ''):
        env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') + f' {flag}=8').strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


DEVICES, VIRTUAL = _ensure_devices()
RING = max(size for size in (2, 4) if size <= len(DEVICES))
# smoke shapes on the virtual mesh (XLA:CPU has no latency-hiding
# scheduler — the rows only prove the sweep runs); real shapes on chips
BATCH, SEQ, DIM, FFN, REPS = ((8, 64, 256, 1024, 5) if VIRTUAL
                              else (8, 1024, 4096, 14336, 20))
CHUNK_COUNTS = (1, 2, 4)


def _chain_scalar(tree):
    total = jnp.float32(0)
    for leaf in jax.tree.leaves(tree):
        total = total + leaf.reshape(-1)[0].astype(jnp.float32)
    return total


def time_fwd_bwd(fn, *args) -> float:
    """Seconds per fwd+bwd over REPS chained iterations (the
    benchmarks/README.md methodology: square loss, gradients folded back
    into the carry, completion forced by a host read)."""
    def loss_fn(*a):
        out = fn(*a)
        return jnp.sum(jnp.square(out.astype(jnp.float32))) * 1e-9

    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(len(args))))

    def body(_, carry):
        loss, grads = vg(*carry)
        feedback = (loss + _chain_scalar(grads)) * 1e-7
        return tuple(a + feedback.astype(a.dtype) for a in carry)

    run = jax.jit(lambda *a: lax.fori_loop(0, REPS, body, a))
    out = run(*args)
    _materialize(out)
    t0 = time.perf_counter()
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


def _report(tag, seconds, note=None):
    entry = {'phase': tag, 'us': round(seconds * 1e6, 1)}
    if note:
        entry['note'] = note
    print(json.dumps(entry))
    return seconds


def _build(include_composed: bool = True):
    """The case table. ``include_composed=False`` skips the composed
    fsdp x model rows — their operands are a SECOND full device_put of
    every tensor onto the composed mesh (~300 MB of extra HBM +
    host-to-device at the real shapes), which ``headline`` never times."""
    from tpusystem.parallel.mesh import FSDP, MeshSpec, shard_map
    from tpusystem.parallel.schedule import (OverlapSchedule, fsdp_plan,
                                             prefetched, scheduled_ffn)
    from tpusystem.parallel.sharding import fsdp_shard_dim

    mesh = MeshSpec(fsdp=RING).build(DEVICES[:RING])
    rng = np.random.default_rng(0)
    dtype = jnp.bfloat16
    x = jnp.asarray(rng.normal(size=(BATCH, SEQ, DIM)) * 0.1, dtype)
    w_up = jnp.asarray(rng.normal(size=(DIM, FFN)) * 0.02, dtype)
    b_up = jnp.asarray(rng.normal(size=(FFN,)) * 0.02, dtype)
    w_down = jnp.asarray(rng.normal(size=(FFN, DIM)) * 0.02, dtype)
    b_down = jnp.asarray(rng.normal(size=(DIM,)) * 0.02, dtype)

    def put(value, spec):
        return jax.device_put(value, NamedSharding(mesh, spec))

    def constrained(value, spec):
        return lax.with_sharding_constraint(value, NamedSharding(mesh, spec))

    # operands pre-placed the ZeRO-3 way: batch over fsdp, each kernel
    # sharded on the dimension the placement policy would pick (the
    # fsdp_shard_dim single source of truth); biases replicated so the
    # rows time the KERNEL collectives, not a rounding-error gather
    up_dim = fsdp_shard_dim(w_up.shape, RING)
    down_dim = fsdp_shard_dim(w_down.shape, RING)
    up_spec = P(*(FSDP if d == up_dim else None for d in range(2)))
    down_spec = P(*(FSDP if d == down_dim else None for d in range(2)))
    x_rows = put(x, P(FSDP, None, None))
    up_sharded = put(w_up, up_spec)
    b_up_repl = put(b_up, P(None))
    down_sharded = put(w_down, down_spec)
    b_down_repl = put(b_down, P(None))

    def manual(body, in_specs, out_specs):
        return shard_map(body, mesh=mesh, check_vma=False,
                         in_specs=in_specs, out_specs=out_specs)

    cases = {}

    # --- weight all-gather + matmul (the up-projection) -----------------
    cases['wg_mm[gspmd]'] = (
        lambda xs, ws: constrained(jnp.matmul(xs, ws), P(FSDP, None, None)),
        (x_rows, up_sharded), 'partitioner-inserted monolithic gather')
    cases['wg_mm[one-shot]'] = (
        manual(lambda xs, ws: jnp.matmul(
            xs, lax.all_gather(ws, FSDP, axis=up_dim, tiled=True)),
            (P(FSDP, None, None), up_spec), P(FSDP, None, None)),
        (x_rows, up_sharded), 'manual all_gather of the kernel, then matmul')
    for chunks in CHUNK_COUNTS:
        plan = fsdp_plan(w_up.shape, RING, chunks=chunks, min_size=1)
        cases[f'wg_mm[overlap c{chunks}]'] = (
            manual(lambda xs, ws, plan=plan: jnp.matmul(
                xs, prefetched(ws, plan)),
                (P(FSDP, None, None), up_spec), P(FSDP, None, None)),
            (x_rows, up_sharded),
            'ring gather custom_vjp, scatter deferred in bwd')

    # --- the whole FFN block --------------------------------------------
    def ffn_gspmd(xs, wu, bu, wd, bd):
        grown = nn.gelu(jnp.matmul(xs, wu) + bu)
        return constrained(jnp.matmul(grown, wd) + bd, P(FSDP, None, None))

    cases['ffn[gspmd]'] = (
        ffn_gspmd, (x_rows, up_sharded, b_up_repl, down_sharded, b_down_repl),
        'monolithic param gathers + grad scatters from the partitioner')

    def ffn_one_shot(xs, wu, bu, wd, bd):
        wu = lax.all_gather(wu, FSDP, axis=up_dim, tiled=True)
        wd = lax.all_gather(wd, FSDP, axis=down_dim, tiled=True)
        grown = nn.gelu(jnp.matmul(xs, wu) + bu)
        return jnp.matmul(grown, wd) + bd

    cases['ffn[one-shot]'] = (
        manual(ffn_one_shot,
               (P(FSDP, None, None), up_spec, P(None), down_spec, P(None)),
               P(FSDP, None, None)),
        (x_rows, up_sharded, b_up_repl, down_sharded, b_down_repl),
        'manual monolithic kernel gathers inside shard_map')

    for chunks in CHUNK_COUNTS:
        schedule = OverlapSchedule(fsdp='prefetch', chunks=chunks,
                                   fsdp_min_size=1)
        cases[f'ffn[overlap c{chunks}]'] = (
            functools.partial(scheduled_ffn, mesh=mesh, schedule=schedule),
            (x_rows, up_sharded, b_up_repl, down_sharded, b_down_repl),
            'both kernel gathers at FFN entry, grad scatters deferred')

    # --- composed: TP rings AND FSDP prefetch under one schedule --------
    if include_composed and RING >= 4:
        from tpusystem.parallel.mesh import MODEL
        composed = MeshSpec(fsdp=2, model=RING // 2).build(DEVICES[:RING])
        xc = jax.device_put(x, NamedSharding(composed, P(FSDP, None, None)))
        wu_c = jax.device_put(w_up, NamedSharding(composed, P(FSDP, MODEL)))
        bu_c = jax.device_put(b_up, NamedSharding(composed, P(MODEL)))
        wd_c = jax.device_put(w_down, NamedSharding(composed, P(MODEL, FSDP)))
        bd_c = jax.device_put(b_down, NamedSharding(composed, P(None)))

        def composed_gspmd(xs, wu, bu, wd, bd):
            grown = lax.with_sharding_constraint(
                nn.gelu(jnp.matmul(xs, wu) + bu),
                NamedSharding(composed, P(FSDP, None, MODEL)))
            return lax.with_sharding_constraint(
                jnp.matmul(grown, wd) + bd,
                NamedSharding(composed, P(FSDP, None, None)))

        cases['composed[gspmd]'] = (
            composed_gspmd, (xc, wu_c, bu_c, wd_c, bd_c),
            'fsdp x model mesh, every collective monolithic')
        schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=2,
                                   fsdp_min_size=1)
        cases['composed[schedule c2]'] = (
            functools.partial(scheduled_ffn, mesh=composed,
                              schedule=schedule),
            (xc, wu_c, bu_c, wd_c, bd_c),
            'TP rings + FSDP prefetch in ONE manual region')

    return cases


def sweep() -> dict[str, float]:
    times = {}
    for tag, (fn, args, note) in _build().items():
        times[tag] = _report(tag, time_fwd_bwd(fn, *args), note=note)
    best_chunks, best = min(
        ((chunks, times[f'ffn[overlap c{chunks}]']) for chunks in CHUNK_COUNTS),
        key=lambda pair: pair[1])
    summary = {
        'mesh': f"{DEVICES[0].platform} fsdp={RING}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'batch': BATCH, 'seq': SEQ, 'dim': DIM, 'ffn': FFN,
        'ffn_us': {tag.split('[')[1][:-1]: round(times[tag] * 1e6, 1)
                   for tag in times if tag.startswith('ffn[')},
        'best_overlap_chunks': best_chunks,
        'overlap_vs_gspmd': round(times['ffn[gspmd]'] / best, 3),
        'overlap_vs_one_shot': round(times['ffn[one-shot]'] / best, 3),
    }
    if 'composed[schedule c2]' in times:
        summary['composed_schedule_vs_gspmd'] = round(
            times['composed[gspmd]'] / times['composed[schedule c2]'], 3)
    print(json.dumps({'summary': summary}))
    return times


def headline() -> None:
    """The single JSON line bench.py forwards as its fsdp_overlap row."""
    cases = _build(include_composed=False)
    picks = ['ffn[gspmd]'] + [f'ffn[overlap c{c}]' for c in CHUNK_COUNTS]
    times = {tag: time_fwd_bwd(cases[tag][0], *cases[tag][1])
             for tag in picks}
    best_chunks, best = min(
        ((chunks, times[f'ffn[overlap c{chunks}]']) for chunks in CHUNK_COUNTS),
        key=lambda pair: pair[1])
    speedup = times['ffn[gspmd]'] / best
    print(json.dumps({
        'metric': 'fsdp_overlap_speedup_vs_gspmd',
        'value': round(speedup, 4),
        'unit': 'x',
        'mesh': f"{DEVICES[0].platform} fsdp={RING}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'chunks': best_chunks,
        'gspmd_us': round(times['ffn[gspmd]'] * 1e6, 1),
        'overlap_us': round(best * 1e6, 1),
    }))


if __name__ == '__main__':
    if 'headline' in sys.argv[1:]:
        headline()
    else:
        sweep()
