"""MoE expert all-to-all hiding: pipelined dispatch vs the one-shot exchange.

The sharded sparse MoE (`tpusystem/ops/moe.py`, quota formulation)
classically exchanges the WHOLE local batch's routed rows over the
expert axis before any expert matmul runs — dispatch, FFN, and return
exchange serialize. The ``moe='overlap'`` arm of the unified scheduler
splits the local rows into microbatch pieces and issues piece k+1's
dispatch ``all_to_all`` under the expert matmuls of piece k (the return
exchange of k rides under the matmuls of k+1). This benchmark times the
MoE layer fwd+bwd both ways:

  moe[one-shot]        single whole-batch exchange (moe='gspmd')
  moe[overlap]         pipelined pieces (moe='overlap', moe_plan-pinned)

All rows are fwd+bwd with the conv_ceiling data-chained discipline.
``python benchmarks/moe_a2a_overlap.py`` prints the table + summary;
``... headline`` prints the single JSON line `bench.py` forwards
(`moe_a2a_overlap_speedup`).

Hardware: uses the real accelerator mesh when >= 2 devices are present
(real numbers); otherwise re-execs itself onto an 8-device virtual CPU
mesh at smoke shapes — same code paths, scheduler-free numbers that only
smoke-test the sweep (XLA:CPU has no latency-hiding scheduler; see
BASELINE.md "pp/moe overlap protocol").
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import os
import time

if os.environ.get('_MOE_A2A_VIRTUAL'):
    from tpusystem.parallel import force_host_platform
    force_host_platform(8)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bench import materialize as _materialize


def _ensure_devices():
    devices = jax.devices()
    if devices[0].platform != 'cpu' and len(devices) >= 2:
        return devices, False
    if devices[0].platform == 'cpu' and len(devices) >= 4:
        return devices, True
    env = dict(os.environ)
    env['_MOE_A2A_VIRTUAL'] = '1'
    flag = '--xla_force_host_platform_device_count'
    if flag not in env.get('XLA_FLAGS', ''):
        env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') + f' {flag}=8').strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


DEVICES, VIRTUAL = _ensure_devices()
EXPERT_AX = max(size for size in (2, 4) if size <= len(DEVICES))
# smoke shapes on the virtual mesh; real shapes on chips
TOKENS, DIM, EXPERTS, REPS = ((512, 128, 4, 5) if VIRTUAL
                              else (8192, 2048, 16, 20))


def time_fwd_bwd(fn, *args) -> float:
    """Seconds per fwd+bwd over REPS chained iterations (the
    benchmarks/README.md methodology)."""
    def loss_fn(*a):
        out, aux = fn(*a)
        return (jnp.sum(jnp.square(out.astype(jnp.float32))) * 1e-9
                + aux * 1e-9)

    vg = jax.value_and_grad(loss_fn, argnums=tuple(range(len(args))))

    def chain(tree):
        total = jnp.float32(0)
        for leaf in jax.tree.leaves(tree):
            total = total + leaf.reshape(-1)[0].astype(jnp.float32)
        return total

    def body(_, carry):
        loss, grads = vg(*carry)
        feedback = (loss + chain(grads)) * 1e-7
        return tuple(jax.tree.map(
            lambda leaf: leaf + feedback.astype(leaf.dtype), a)
            for a in carry)

    run = jax.jit(lambda *a: lax.fori_loop(0, REPS, body, a))
    out = run(*args)
    _materialize(out)
    t0 = time.perf_counter()
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


def _build():
    from tpusystem.ops.moe import MoEMLP
    from tpusystem.parallel import (MeshSpec, OverlapSchedule, ShardingPolicy,
                                    batch_sharding, moe_plan)

    data = len(DEVICES) // EXPERT_AX
    mesh = MeshSpec(data=data, expert=EXPERT_AX).build(DEVICES)
    rng = np.random.default_rng(0)
    dtype = jnp.float32 if VIRTUAL else jnp.bfloat16
    hidden = jnp.asarray(rng.normal(size=(TOKENS, DIM)) * 0.1, jnp.float32)
    local_rows = TOKENS // (data * EXPERT_AX)
    assert moe_plan(local_rows, EXPERT_AX).path == 'overlap', (
        'shape must pipeline for the A/B to mean anything')

    def layer(schedule):
        module = MoEMLP(EXPERTS, dtype=dtype, mesh=mesh,
                        capacity_factor=2.0, schedule=schedule)
        params = module.init(jax.random.PRNGKey(0), hidden[:8])['params']
        from tpusystem.ops.moe import moe_partition_rules
        params = ShardingPolicy(rules=tuple(
            (pattern.replace('moe/', ''), spec)
            for pattern, spec in moe_partition_rules())).place(params, mesh)
        placed = jax.device_put(hidden, batch_sharding(mesh))

        def fn(x, params):
            return module.apply({'params': params}, x)
        return fn, (placed, params)

    cases = {}
    fn, args = layer(None)
    cases['moe[one-shot]'] = (fn, args,
                              'whole-batch exchange before any expert matmul')
    fn, args = layer(OverlapSchedule(moe='overlap'))
    cases['moe[overlap]'] = (fn, args,
                             'piece k+1 dispatch under expert matmuls of k')
    return cases


def sweep() -> dict[str, float]:
    times = {}
    for tag, (fn, args, note) in _build().items():
        seconds = time_fwd_bwd(fn, *args)
        times[tag] = seconds
        print(json.dumps({'phase': tag, 'us': round(seconds * 1e6, 1),
                          'note': note}))
    print(json.dumps({'summary': {
        'mesh': f"{DEVICES[0].platform} expert={EXPERT_AX}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'tokens': TOKENS, 'dim': DIM, 'experts': EXPERTS,
        'overlap_vs_one_shot': round(times['moe[one-shot]']
                                     / times['moe[overlap]'], 3),
    }}))
    return times


def headline() -> None:
    """The single JSON line bench.py forwards as its moe_a2a row."""
    times = {tag: time_fwd_bwd(fn, *args)
             for tag, (fn, args, _) in _build().items()}
    print(json.dumps({
        'metric': 'moe_a2a_overlap_speedup',
        'value': round(times['moe[one-shot]'] / times['moe[overlap]'], 4),
        'unit': 'x',
        'mesh': f"{DEVICES[0].platform} expert={EXPERT_AX}"
                + (' (virtual smoke)' if VIRTUAL else ''),
        'one_shot_us': round(times['moe[one-shot]'] * 1e6, 1),
        'overlap_us': round(times['moe[overlap]'] * 1e6, 1),
    }))


if __name__ == '__main__':
    if 'headline' in sys.argv[1:]:
        headline()
    else:
        sweep()
