"""Elastic resize cost: hot-reshard vs disk-restore wall clock.

The elastic loop's (`tpusystem/parallel/elastic.py`) promise is that a
preemption wave costs a *reshard*, not a cold restart — so the number
that matters is how long the reshard's state reassembly takes against
the alternative it replaces, a disk restore onto the shrunk mesh:

1. ``hot reshard`` — 4 virtual hosts shrink to 2: merge every host's
   in-memory :class:`ShardedLeaf` pieces (`merge_hot`), reassemble and
   re-lay the training state onto the 2-device mesh's shardings
   (`elastic_resume` -> source ``hot-reshard``);
2. ``disk restore`` — the same step restored from the newest committed
   Orbax checkpoint onto the same shrunk mesh (`checkpointer.resume`,
   what a non-elastic restart would pay *after* the relaunch).

Both arms are medians of TRIALS runs on the tiny model, both end with
the params materialized on host. On a multi-chip TPU the real devices
are used; elsewhere the CPU platform is forced to 4 virtual chips —
smoke numbers, same protocol.

Every row is one machine-readable JSON line; the LAST line is the
``resize_seconds`` headline ``bench.py`` forwards.

Run: ``python benchmarks/elastic_resize.py [headline]``.
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import os
import tempfile
import time

if os.environ.get('_ELASTIC_RESIZE_VIRTUAL'):
    from tpusystem.parallel import force_host_platform
    force_host_platform(4)

import jax

TRIALS = 3


def _ensure_devices():
    """Real 4-chip mesh when it exists; else re-exec onto a 4-device
    virtual CPU mesh (force_host_platform must precede backend init, so
    a fresh process is the only clean path — the fsdp_overlap pattern)."""
    devices = jax.devices()
    if len(devices) >= 4:
        return devices[:4]
    env = dict(os.environ)
    env['_ELASTIC_RESIZE_VIRTUAL'] = '1'
    env['JAX_PLATFORMS'] = 'cpu'
    flag = '--xla_force_host_platform_device_count'
    if flag not in env.get('XLA_FLAGS', ''):
        env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') + f' {flag}=4').strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main() -> None:
    import jax.numpy as jnp
    import numpy as np

    from bench import materialize
    from tpusystem.checkpoint import Checkpointer
    from tpusystem.checkpoint.memstore import HotState, blob_digest
    from tpusystem.models import gpt2_tiny
    from tpusystem.parallel import MeshSpec, TensorParallel, batch_sharding
    from tpusystem.parallel.elastic import elastic_resume, split_pieces
    from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                                 flax_apply, init_state)

    devices = _ensure_devices()
    identity = 'bench-elastic'
    spec = MeshSpec(fsdp=4)
    mesh4 = spec.build(devices)
    module = gpt2_tiny()
    optimizer = AdamW(lr=1e-3)
    policy = TensorParallel(module.partition_rules(), fsdp=True,
                            fsdp_min_size=64)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 32)), jnp.int32)
    state = policy.place(init_state(module, optimizer, tokens[:1]), mesh4)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    state, _ = step(state, jax.device_put(tokens, batch_sharding(mesh4)),
                    jax.device_put(tokens, batch_sharding(mesh4)))
    at = int(state.step)
    entries = [HotState(step=at, digest=blob_digest(blob), blob=blob)
               for blob in split_pieces(state, mesh4, hosts=4)]

    mesh2 = spec.resized(2).build(devices[:2])
    blank = policy.place(init_state(module, optimizer, tokens[:1]), mesh2)
    with tempfile.TemporaryDirectory() as root, \
            Checkpointer(root, async_save=False) as checkpointer:
        checkpointer.save(identity, at, state, extras={'step': at})

        def timed(contributions):
            times = []
            for _ in range(TRIALS):
                start = time.perf_counter()
                restored, _, _, source = elastic_resume(
                    checkpointer, identity, blank, contributions)
                materialize(restored.params)
                times.append(time.perf_counter() - start)
            return source, sorted(times)[len(times) // 2]

        hot_source, hot = timed(entries)
        disk_source, disk = timed([])      # no pieces: the disk rung
    assert (hot_source, disk_source) == ('hot-reshard', 'disk'), (
        hot_source, disk_source)
    print(json.dumps({
        'metric': 'resize_seconds',
        'value': round(hot, 4),
        'unit': 's (hot reshard 4->2 hosts, tiny model)',
        'disk_seconds': round(disk, 4),
        'hot_speedup_vs_disk': round(disk / hot, 2) if hot else None,
    }))


if __name__ == '__main__':
    main()        # 'headline' arg tolerated: the one row IS the headline
