"""Sparse vs dense MoE dispatch on one chip: tokens/s fwd+bwd, the
dense formulation's memory cliff (BASELINE.md round-2 numbers), and the
three-way scatter/gather/fused sparse-impl comparison (round 6).
"""
import sys, time, json
sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
import jax, jax.numpy as jnp, numpy as np
from functools import partial
from tpusystem.ops import MoEMLP

def bench(dispatch, experts, tokens=8192, dim=768, steps=20,
          sparse_impl='gather'):
    module = MoEMLP(experts=experts, k=2, dtype=jnp.bfloat16, dispatch=dispatch,
                    sparse_impl=sparse_impl)
    hidden = jax.random.normal(jax.random.PRNGKey(0), (tokens // 512, 512, dim), jnp.bfloat16)
    params = module.init(jax.random.PRNGKey(1), hidden)['params']

    def loss(p, h):
        out, aux = module.apply({'params': p}, h)
        return jnp.mean(out.astype(jnp.float32) ** 2) + aux


    grad = jax.value_and_grad(loss, argnums=(0, 1))
    @jax.jit
    def run(p, h):
        # chain h through its gradient (stops XLA hoisting the invariant
        # fwd+bwd out of the loop) and keep every weight gradient alive
        def body(carry, _):
            h, acc = carry
            l, (gp, gh) = grad(p, h)
            acc = acc + l + sum(g.astype(jnp.float32).mean()
                                for g in jax.tree.leaves(gp))
            return ((h + gh.astype(h.dtype)), acc), None
        (h, acc), _ = jax.lax.scan(body, (h, jnp.float32(0)), None,
                                   length=steps)
        return acc + h.astype(jnp.float32).mean()

    float(run(params, hidden))  # compile
    start = time.perf_counter()
    float(run(params, hidden))
    dt = time.perf_counter() - start
    tps = tokens * steps / dt
    tag = dispatch if dispatch != 'sparse' else f'sparse[{sparse_impl}]'
    print(json.dumps({"dispatch": tag, "experts": experts,
                      "tokens_per_s": round(tps), "ms_per_step": round(dt/steps*1e3, 2)}))
    return tps

for experts in (8, 32, 64):
    d = bench('dense', experts)
    s = bench('sparse', experts)
    print(f'experts={experts}: sparse/dense speedup = {s/d:.2f}x')

# three-way single-chip row movement: the scatter formulation, the
# scatter-free gather custom_vjp pair, and the fused Pallas grouped
# gather-matmul (dispatch in the up-matmul's loads, weighted combine in
# the down-matmul's epilogue) — fwd+bwd tokens/s at the headline shapes
print('--- sparse impls: scatter vs gather vs fused, 8 experts ---')
impl_tps = {impl: bench('sparse', 8, sparse_impl=impl)
            for impl in ('scatter', 'gather', 'fused')}
print(f"fused/gather speedup = {impl_tps['fused']/impl_tps['gather']:.2f}x, "
      f"gather/scatter = {impl_tps['gather']/impl_tps['scatter']:.2f}x")

# the cliff: at 16k tokens x 64 experts the dense routing tensors are
# ~1.3 GB each (+ gradients) -- RESOURCE_EXHAUSTED on a 16 GB chip, while
# the sparse path keeps scaling
print('--- 16k/32k tokens, 64 experts, sparse only ---')
bench('sparse', 64, tokens=16384)
bench('sparse', 64, tokens=32768)


def exchanged_bytes(experts=64, devices=8, tokens=65536, dim=4096, k=2,
                    capacity_factor=1.25, skew=0.0, seed=0):
    """ICI bytes per MoE layer for the quota'd all_to_all vs the ragged
    exchange, from actual router statistics (the quota path ships its full
    static buffer regardless of routing; ragged ships the routed rows).

    ``skew`` > 0 biases the router toward a subset of experts, the regime
    where the quota path both pads *and* drops.
    """
    rng = np.random.default_rng(seed)
    local_rows = tokens // devices
    logits = rng.normal(size=(tokens, experts)).astype(np.float32)
    if skew:
        logits[:, : experts // 4] += skew
    top = np.argsort(-logits, axis=1)[:, :k]
    bytes_per_row = dim * 2                      # bf16 activations
    # quota path: every sender ships experts*quota rows, twice (there+back)
    quota = max(1, min(local_rows, int(local_rows * k * capacity_factor
                                       / experts)))
    quota_bytes = devices * experts * quota * bytes_per_row * 2
    # ragged path: each sender ships its actual kept assignments, capped at
    # min(local_rows, group capacity) per expert
    group_capacity = max(1, min(tokens, int(tokens * k * capacity_factor
                                            / experts)))
    send_cap = min(local_rows, group_capacity)
    ragged_rows = 0
    for d in range(devices):
        mine = top[d * local_rows:(d + 1) * local_rows].reshape(-1)
        counts = np.bincount(mine, minlength=experts)
        ragged_rows += np.minimum(counts, send_cap).sum()
    ragged_bytes = int(ragged_rows) * bytes_per_row * 2
    print(json.dumps({
        "experts": experts, "devices": devices, "tokens": tokens,
        "skew": skew, "quota_MB": round(quota_bytes / 2**20, 1),
        "ragged_MB": round(ragged_bytes / 2**20, 1),
        "ragged_over_quota": round(ragged_bytes / quota_bytes, 3)}))


print('--- exchanged bytes per layer, quota vs ragged a2a ---')
exchanged_bytes(skew=0.0)
exchanged_bytes(skew=1.5)
