"""MoE whole-model ceiling: decompose the 0.416 active-MFU row.

BASELINE.md's MoE whole-model row (GPT-2 125M body, 8 experts / top-2
every second block, b16 s1024, sparse dispatch) is the one measured row
under the 0.50 north-star without a ceiling argument. This benchmark
times every phase of the MoE layer *at the whole-model row's shapes*
(dim 768, hidden 3072, tokens 16384, capacity 5120), fwd+bwd, with the
conv_ceiling data-chained discipline (each rep folds a scalar of the
phase's gradient back into the carried input, so neither the forward nor
any gradient is hoisted or dead-code-eliminated):

  router     f32 logits matmul + softmax + top_k + renormalize
  seating    the integer sort/seat machinery of route_top_k_sparse
  dispatch   token-row gather + scatter into the [E*C, D] expert buffer
  expert_ffn the per-expert ecd,edh/ech,ehd einsum pair (the MXU work)
  combine    buffer gather + weighted scatter-add back to token order
  fused      the Pallas grouped gather-matmul pair: dispatch riding the
             up-projection's loads, the weighted combine riding the
             down-projection's epilogue (no standalone row movement)
  moe_layer  the full MoEMLP, all three sparse impls
             (scatter / gather / fused — the three-way table)
  dense_ffn  the fc/gelu/proj block at the same token count (reference)

`python benchmarks/moe_ceiling.py [whole [scatter|gather|fused]]` —
`whole` additionally re-measures the end-to-end 323M-param train step
(the BASELINE row) with the chosen single-shard row movement.

Accounting note: active-MFU charges k=2 experts' FLOPs per token, but
the capacity-factor buffer executes k*cf = 2.5 experts' worth — the FFN
phase alone cannot exceed k/(k*cf) = 0.80 of the matmul rate in
active-FLOPs terms. That structural factor plus the measured routing /
dispatch / combine time IS the ceiling this file pins.
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from bench import materialize as _materialize, peak_flops

DIM, RATIO, EXPERTS, K, CF = 768, 4, 8, 2, 1.25
TOKENS = 16 * 1024                       # b16 s1024
HIDDEN = RATIO * DIM
REPS = 50


def _chain_scalar(tree):
    """One element of every leaf, summed — the data-dependency probe."""
    total = jnp.float32(0)
    for leaf in jax.tree.leaves(tree):
        total = total + leaf.reshape(-1)[0].astype(jnp.float32)
    return total


def _has_float(tree) -> bool:
    return any(jnp.issubdtype(leaf.dtype, jnp.inexact)
               for leaf in jax.tree.leaves(tree))


def _fold(tree, feedback):
    return jax.tree.map(
        lambda leaf: leaf + feedback.astype(leaf.dtype)
        if jnp.issubdtype(leaf.dtype, jnp.inexact) else leaf, tree)


def time_fwd_bwd(fn, *args) -> float:
    """Seconds per fwd+bwd of ``fn(*args) -> array`` over REPS chained
    iterations. Every float arg (pytrees allowed) gets its gradient
    computed and folded into the carry (no DCE), the loss feeds the next
    iteration's inputs (no hoisting), and the loss is a *sum of squares*
    so the output cotangent is data-dependent — a constant cotangent
    lets XLA collapse backward matmuls of broadcast rows to O(D*H)
    (measured: 'impossible' >1 MFU on the FFN phase with a linear
    loss)."""
    grad_argnums = tuple(i for i, a in enumerate(args) if _has_float(a))

    def loss_fn(*a):
        out = fn(*a)
        return jnp.sum(jnp.square(out.astype(jnp.float32))) * 1e-9

    vg = jax.value_and_grad(loss_fn, argnums=grad_argnums)

    def body(_, carry):
        loss, grads = vg(*carry)
        feedback = ((loss + _chain_scalar(grads)) * 1e-7)
        return tuple(
            _fold(a, feedback) if i in grad_argnums else a
            for i, a in enumerate(carry))

    run = jax.jit(lambda *a: jax.lax.fori_loop(0, REPS, body, a))
    out = run(*args)
    _materialize(out)       # the tunneled platform's block_until_ready
    t0 = time.perf_counter()          # does NOT wait; force a host read
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


def time_fwd(fn, *args) -> float:
    """Forward-only variant (integer phases have no gradient)."""
    def body(_, carry):
        out = fn(*carry)
        feedback = _chain_scalar(out) * 1e-7
        return tuple(a + feedback.astype(a.dtype)
                     if jnp.issubdtype(a.dtype, jnp.inexact) else a
                     for a in carry)
    run = jax.jit(lambda *a: jax.lax.fori_loop(0, REPS, body, a))
    out = run(*args)
    _materialize(out)       # the tunneled platform's block_until_ready
    t0 = time.perf_counter()          # does NOT wait; force a host read
    out = run(*args)
    _materialize(out)
    return (time.perf_counter() - t0) / REPS


def phases() -> None:
    from tpusystem.ops.moe import (MoEMLP, expert_capacity,
                                   route_top_k_sparse)

    peak = peak_flops(jax.devices()[0])
    rng = np.random.default_rng(0)
    capacity = expert_capacity(TOKENS, EXPERTS, K, CF)
    flat = jnp.asarray(rng.normal(size=(TOKENS, DIM)) * 0.1, jnp.bfloat16)
    router = jnp.asarray(rng.normal(size=(DIM, EXPERTS)) * 0.02, jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(EXPERTS, DIM, HIDDEN)) * 0.02,
                     jnp.float32)
    b1 = jnp.zeros((EXPERTS, HIDDEN), jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(EXPERTS, HIDDEN, DIM)) * 0.02,
                     jnp.float32)
    b2 = jnp.zeros((EXPERTS, DIM), jnp.float32)

    def report(tag, seconds, flops=None, note=None):
        entry = {'phase': tag, 'us': round(seconds * 1e6, 1)}
        if flops:
            entry['mfu'] = round(flops / seconds / peak, 3)
        if note:
            entry['note'] = note
        print(json.dumps(entry))
        return seconds

    # --- router: f32 matmul + softmax + top_k + renorm ------------------
    def router_phase(flat, router):
        logits = flat.astype(jnp.float32) @ router
        gates = jax.nn.softmax(logits)
        top_gates, _ = jax.lax.top_k(gates, K)
        return top_gates / (jnp.sum(top_gates, -1, keepdims=True) + 1e-9)

    t_router = report('router', time_fwd_bwd(router_phase, flat, router),
                      flops=3 * 2 * TOKENS * DIM * EXPERTS)

    # --- seating: integer sort/rank machinery ---------------------------
    gates = jax.nn.softmax(flat.astype(jnp.float32) @ router)

    def seating_phase(gates):
        token_ids, slots, weights, fraction = route_top_k_sparse(
            gates, K, capacity)
        # fold ints through float so the chain probe has a float leaf
        return (weights + slots.astype(jnp.float32) * 1e-12,)

    t_seating = report('seating', time_fwd(seating_phase, gates))

    token_ids, slots, weights, _ = route_top_k_sparse(gates, K, capacity)

    # --- dispatch: gather rows + scatter into the expert buffer ---------
    def dispatch_phase(flat):
        rows = flat[token_ids]
        buffer = jnp.zeros((EXPERTS * capacity, DIM), flat.dtype)
        return buffer.at[slots].set(rows, mode='drop')

    t_dispatch = report('dispatch[scatter]', time_fwd_bwd(dispatch_phase, flat),
                        note='gather[kN,D] + row scatter into [E*C,D]')

    # --- the scatter-free custom_vjp alternative ------------------------
    from tpusystem.ops.moe import (_gather_combine, _gather_dispatch,
                                   _invert_seating)
    slot_asg, slot_token, slots_by_choice = _invert_seating(
        slots, K, TOKENS, EXPERTS * capacity)

    t_dispatch_g = report(
        'dispatch[gather]',
        time_fwd_bwd(lambda f: _gather_dispatch(f, slot_token,
                                                slots_by_choice), flat),
        note='inverse-map gather; bwd = k gathers + sum')

    # --- expert FFN: the MXU phase --------------------------------------
    expert_in = dispatch_phase(flat).reshape(EXPERTS, capacity, DIM)

    def ffn_phase(expert_in, w1, b1, w2, b2):
        compute = jnp.bfloat16
        grown = jnp.einsum('ecd,edh->ech', expert_in, w1.astype(compute))
        grown = nn.gelu(grown + b1[:, None].astype(compute))
        return (jnp.einsum('ech,ehd->ecd', grown, w2.astype(compute))
                + b2[:, None].astype(compute))

    ffn_flops = 3 * 2 * 2 * EXPERTS * capacity * DIM * HIDDEN  # fwd+bwd
    t_ffn = report('expert_ffn',
                   time_fwd_bwd(ffn_phase, expert_in, w1, b1, w2, b2),
                   flops=ffn_flops,
                   note=f'[{EXPERTS},{capacity},{DIM}]x[{EXPERTS},{DIM},'
                        f'{HIDDEN}] pair')

    # --- combine: buffer gather + weighted scatter-add ------------------
    buffer = ffn_phase(expert_in, w1, b1, w2, b2).reshape(
        EXPERTS * capacity, DIM)

    def combine_phase(buffer, weights):
        gathered = buffer.at[slots].get(mode='fill', fill_value=0)
        return jnp.zeros((TOKENS, DIM), buffer.dtype).at[token_ids].add(
            gathered * weights[:, None].astype(buffer.dtype))

    t_combine = report('combine[scatter]',
                       time_fwd_bwd(combine_phase, buffer, weights),
                       note='gather[kN,D] + scatter-add to token order')

    t_combine_g = report(
        'combine[gather]',
        time_fwd_bwd(lambda b, w: _gather_combine(b, w, slots_by_choice,
                                                  slot_token, slot_asg),
                     buffer, weights),
        note='k gathers + weighted sum; bwd gathers only')

    # --- fused kernel phases: the data movement rides the matmuls -------
    # (forward-only rows: the kernels' backwards ARE the same kernels with
    # swapped operands, measured through moe_layer[fused] below. MFU here
    # charges the executed matmul FLOPs — compare dispatch[gather] +
    # half of expert_ffn against dispatch+up[fused]. Seating arrays are
    # the slot_asg/slot_token/slots_by_choice computed above, so the
    # fused rows measure exactly the seating the gather rows measure.)
    from tpusystem.ops.pallas.grouped_matmul import (gather_rows_matmul,
                                                     matmul_scatter_rows)

    clamped = jnp.minimum(slot_token, TOKENS - 1)
    valid = (slot_token < TOKENS).astype(jnp.float32)
    w_slot = weights.at[slot_asg].get(mode='fill', fill_value=0)
    w1c, b1c = w1.astype(jnp.bfloat16), b1.astype(jnp.bfloat16)
    w2c, b2c = w2.astype(jnp.bfloat16), b2.astype(jnp.bfloat16)

    up_flops = 2 * EXPERTS * capacity * DIM * HIDDEN
    t_fused_up = report(
        'dispatch+up_mm[fused]',
        time_fwd(lambda f: gather_rows_matmul(f, w1c, clamped, valid,
                                              rows_per_group=capacity),
                 flat),
        flops=up_flops,
        note='rows DMA from unpermuted tokens into the MXU tiles')

    grown = nn.gelu(dispatch_phase(flat).reshape(EXPERTS, capacity, DIM)
                    @ w1c + b1c[:, None]).reshape(EXPERTS * capacity, HIDDEN)

    t_fused_down = report(
        'down_mm+combine[fused]',
        time_fwd(lambda g: matmul_scatter_rows(
            g, w2c, b2c, slot_token, w_slot, TOKENS,
            rows_per_group=capacity)[0], grown),
        flops=up_flops,
        note='k-way weighted combine in the matmul epilogue (RMW rows)')

    # --- whole MoE layer, all three impls -------------------------------
    t_by_impl = {}
    for impl in ('scatter', 'gather', 'fused'):
        layer = MoEMLP(EXPERTS, k=K, mlp_ratio=RATIO, capacity_factor=CF,
                       dispatch='sparse', sparse_impl=impl)
        variables = layer.init(jax.random.PRNGKey(0), flat[:64])

        def layer_phase(flat, params, layer=layer):
            out, aux = layer.apply({'params': params}, flat)
            return out.astype(jnp.float32) + aux

        t_by_impl[impl] = report(
            f'moe_layer[{impl}]',
            time_fwd_bwd(layer_phase, flat, variables['params']))
    t_layer = min(t_by_impl.values())

    # --- dense FFN reference at the same token count --------------------
    wf = jnp.asarray(rng.normal(size=(DIM, HIDDEN)) * 0.02, jnp.float32)
    wp = jnp.asarray(rng.normal(size=(HIDDEN, DIM)) * 0.02, jnp.float32)

    def dense_phase(flat, wf, wp):
        compute = jnp.bfloat16
        grown = nn.gelu(flat @ wf.astype(compute))
        return grown @ wp.astype(compute)

    dense_flops = 3 * 2 * 2 * TOKENS * DIM * HIDDEN
    t_dense = report('dense_ffn', time_fwd_bwd(dense_phase, flat, wf, wp),
                     flops=dense_flops)

    overhead = t_layer - t_ffn
    active_ffn_flops = 3 * 2 * 2 * K * TOKENS * DIM * HIDDEN  # what MFU charges
    print(json.dumps({
        'summary': {
            'phase_sum_us': round((t_router + t_seating + t_dispatch
                                   + t_ffn + t_combine) * 1e6, 1),
            'layer_us_by_impl': {impl: round(t * 1e6, 1)
                                 for impl, t in t_by_impl.items()},
            'fused_up_us': round(t_fused_up * 1e6, 1),
            'fused_down_us': round(t_fused_down * 1e6, 1),
            'moe_layer_us': round(t_layer * 1e6, 1),
            'dense_ffn_us': round(t_dense * 1e6, 1),
            'layer_vs_dense': round(t_layer / t_dense, 2),
            'routing_overhead_pct': round(100 * overhead / t_layer, 1),
            'structural_cap': round(K / (K * CF), 3),
            'active_mfu_ceiling_ffn_only': round(
                active_ffn_flops / t_layer / peak_flops(jax.devices()[0]), 3),
        }}))


def whole_model(sparse_impl: str = 'gather') -> None:
    """Re-measure the BASELINE whole-model MoE row (323M / 153M active).

    ``python benchmarks/moe_ceiling.py whole [scatter|gather|fused]``
    selects the single-shard row movement (BASELINE.md compares the
    gather row against the fused grouped gather-matmul row)."""
    from tpusystem.models import GPT2
    from tpusystem.train import (AdamW, ChunkedNextTokenLoss, WithAuxLoss,
                                 build_train_step, flax_apply, init_state)

    batch, seq, steps = 16, 1024, 30
    module = GPT2(dropout=0.0, attention='flash', vocab_size=50304,
                  return_features=True, moe_experts=EXPERTS, moe_every=2,
                  moe_sparse_impl=sparse_impl)
    optimizer = AdamW(lr=3e-4, grad_clip=1.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 50257, (batch, seq)), jnp.int32)
    state = init_state(module, optimizer, tokens[:1, :8])
    step = build_train_step(flax_apply(module),
                            WithAuxLoss(ChunkedNextTokenLoss(chunks=8)),
                            optimizer, jit=False)

    @partial(jax.jit, donate_argnums=0)
    def run(state, tokens):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, tokens, tokens)[0], state)

    state = run(state, tokens)
    float(jax.tree.leaves(state.params)[0].sum())
    t0 = time.perf_counter()
    state = run(state, tokens)
    float(jax.tree.leaves(state.params)[0].sum())
    elapsed = time.perf_counter() - t0

    params_count = sum(leaf.size for leaf in jax.tree.leaves(state.params))
    # active params: total minus the (experts - k) inactive experts' FFNs
    per_expert = DIM * HIDDEN * 2 + HIDDEN + DIM
    moe_layers = module.layers // 2
    active = params_count - moe_layers * (EXPERTS - K) * per_expert
    head_dim = module.dim // module.heads
    attention_flops = (12 * module.layers * module.heads * seq * seq
                       * head_dim * batch)
    step_flops = 6 * active * batch * seq + attention_flops
    mfu = step_flops * steps / elapsed / peak_flops(jax.devices()[0])
    print(json.dumps({
        'whole_model': {'sparse_impl': sparse_impl,
                        'params_m': round(params_count / 1e6, 1),
                        'active_m': round(active / 1e6, 1),
                        'steps_per_s': round(steps / elapsed, 2),
                        'tok_per_s': round(batch * seq * steps / elapsed),
                        'active_mfu': round(mfu, 4)}}))


if __name__ == '__main__':
    if 'whole' in sys.argv[1:]:
        impls = [a for a in sys.argv[1:]
                 if a in ('scatter', 'gather', 'fused')]
        whole_model(*impls[:1])
    else:
        phases()
