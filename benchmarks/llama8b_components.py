"""Measure 8B-dimension components on 1x v5e: (a) one LlamaBlock fwd+bwd,
(b) the 128k-vocab chunked LM head. Iterations are chained through the
inputs so XLA cannot hoist the gradient out of the timing loop."""
import sys, time, json
sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
import jax, jax.numpy as jnp, numpy as np

PEAK = 197e12
DIM, FFN, HEADS, KV, VOCAB = 4096, 14336, 32, 8, 128256

def timed(run, *args, steps=8):
    out = run(*args)
    float(jax.tree.leaves(out)[0].sum())
    start = time.perf_counter()
    out = run(*args)
    float(jax.tree.leaves(out)[0].sum())
    return (time.perf_counter() - start) / steps

def block_mfu(batch, seq, steps=8):
    from tpusystem.models.llama import LlamaBlock
    block = LlamaBlock(heads=HEADS, kv_heads=KV, ffn_dim=FFN,
                       dtype=jnp.bfloat16, attention='flash', max_seq=seq)
    hidden = jax.random.normal(jax.random.PRNGKey(0), (batch, seq, DIM), jnp.bfloat16)
    params = block.init(jax.random.PRNGKey(1), hidden)['params']
    pcount = sum(l.size for l in jax.tree.leaves(params))

    def loss(p, h):
        return jnp.mean(block.apply({'params': p}, h, True).astype(jnp.float32) ** 2)

    grad = jax.value_and_grad(loss, argnums=(0, 1))
    @jax.jit
    def run(p, h):
        def body(carry, _):
            h, acc = carry
            l, (gp, gh) = grad(p, h)
            # chain h through its gradient so iterations stay sequential,
            # and fold EVERY weight gradient into the output so XLA cannot
            # dead-code-eliminate the wgrad matmuls (a silent 1.5x cheat)
            acc = acc + sum(g.astype(jnp.float32).mean()
                            for g in jax.tree.leaves(gp))
            return ((h + gh.astype(h.dtype)), acc + l), None
        (h, acc), _ = jax.lax.scan(body, (h, jnp.float32(0)), None, length=steps)
        return acc + h.astype(jnp.float32).mean()

    dt = timed(run, params, hidden, steps=steps)
    flops = 6 * pcount * batch * seq + 12 * HEADS * seq * seq * (DIM // HEADS) * batch
    mfu = flops / dt / PEAK
    print(json.dumps({"component": "block", "batch": batch, "seq": seq,
                      "ms": round(dt*1e3, 2), "mfu": round(mfu, 4)}))
    return mfu, flops / (batch * seq)

def head_mfu(batch, seq, chunks=16, steps=4):
    from tpusystem.train import ChunkedNextTokenLoss
    crit = ChunkedNextTokenLoss(chunks=chunks, tied=False)
    feats = jax.random.normal(jax.random.PRNGKey(0), (batch, seq, DIM), jnp.bfloat16)
    table = jax.random.normal(jax.random.PRNGKey(1), (DIM, VOCAB), jnp.bfloat16) * 0.02
    tokens = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0, VOCAB)

    grad = jax.value_and_grad(lambda f, t: crit((f, t), tokens), argnums=(0, 1))
    @jax.jit
    def run(f, t):
        def body(carry, _):
            f, acc = carry
            l, (gf, gt) = grad(f, t)
            # keep the table wgrad alive (see block_mfu)
            acc = acc + gt.astype(jnp.float32).mean()
            return ((f + gf.astype(f.dtype)), acc + l), None
        (f, acc), _ = jax.lax.scan(body, (f, jnp.float32(0)), None, length=steps)
        return acc + f.astype(jnp.float32).mean()
    dt = timed(run, feats, table, steps=steps)
    flops = 6 * DIM * VOCAB * batch * seq
    mfu = flops / dt / PEAK
    print(json.dumps({"component": "head+chunked_loss", "batch": batch, "seq": seq,
                      "ms": round(dt*1e3, 2), "mfu": round(mfu, 4)}))
    return mfu, flops / (batch * seq)

bm, bft = block_mfu(batch=1, seq=8192)
bm2, _ = block_mfu(batch=2, seq=4096)
hm, hft = head_mfu(batch=1, seq=8192)
total_ft = 32 * bft + hft
proj = total_ft / (32 * bft / bm + hft / hm)
print(json.dumps({"projected_8b_mfu_v5e_components": round(proj, 4),
                  "block_share": round(32*bft/total_ft, 3)}))
