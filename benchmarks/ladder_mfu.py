"""MFU for workload-ladder rows 2 (classifier) and 3 (ResNet-50) — the
same unit as the ladder-4 headline (`bench.py`), same anti-hoisting
methodology (steps chained through the carried TrainState inside one jit,
completion forced by materializing a value).

FLOPs per step come from XLA's own cost model on the compiled single-step
program (`compile().cost_analysis()['flops']`): it counts the executed
fwd+bwd+optimizer HLO, so the number is an *executed*-FLOPs utilization —
marginally above a hand-counted model-FLOPs MFU (optimizer/elementwise
included), stated as such in BASELINE.md.
"""
import sys, time, json, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
from functools import partial

import jax, jax.numpy as jnp, numpy as np

from bench import peak_flops
from tpusystem.models import MLP, ResNet
from tpusystem.train import (AdamW, CrossEntropyLoss, build_train_step,
                             flax_apply, init_state)


def _flops(compiled) -> float:
    """XLA cost-model FLOPs per executed program; ``cost_analysis()``
    returns a dict on current jax and a one-element list of dicts on the
    0.4.x pins — accept both."""
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return float(analysis.get('flops', 0.0))


def measure(tag, module, inputs, targets, steps):
    optimizer = AdamW(lr=1e-3)
    state = init_state(module, optimizer, inputs[:1])
    step = build_train_step(flax_apply(module), CrossEntropyLoss(),
                            optimizer, jit=False)

    single = jax.jit(lambda st: step(st, inputs, targets)[0])
    flops = _flops(single.lower(state).compile())

    @partial(jax.jit, donate_argnums=0)
    def run(state):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, inputs, targets)[0], state)

    state = run(state)
    float(jax.tree.leaves(state.params)[0].sum())     # force completion
    start = time.perf_counter()
    state = run(state)
    float(jax.tree.leaves(state.params)[0].sum())
    elapsed = time.perf_counter() - start

    steps_per_sec = steps / elapsed
    peak = peak_flops(jax.devices()[0])
    result = {
        'workload': tag, 'steps_per_sec': round(steps_per_sec, 2),
        'flops_per_step': float(flops),
        'examples_per_sec': round(steps_per_sec * inputs.shape[0], 1),
    }
    if peak:
        result['mfu'] = round(flops * steps_per_sec / peak, 4)
    print(json.dumps(result))


def composed_row(steps: int = 20):
    """The composed-mesh ladder row: dp x fsdp x tp x stage with ALL four
    overlap arms on (`OverlapSchedule(tp='overlap', fsdp='prefetch',
    pp='overlap', moe='overlap')`) — the measurable row behind ROADMAP
    item 3's >= 0.60-MFU target. A pipelined MoE GPT-2 trains on the
    first 8 devices; needs 8+ chips and a jaxlib that lowers the
    pipeline's partial-manual shard_map (PP x TP) — prints a skip row
    otherwise so single-chip/CPU ladder runs stay green."""
    devices = jax.devices()
    if len(devices) < 8:
        print(json.dumps({'workload': 'composed_gpt2_pp_tp_fsdp_moe',
                          'mfu': None,
                          'note': f'skipped: needs 8 devices, have '
                                  f'{len(devices)}'}))
        return
    from tpusystem.parallel import (MeshSpec, OverlapSchedule,
                                    PipelineParallel, batch_sharding)
    from tpusystem.parallel.mesh import partial_manual_skip_reason
    reason = partial_manual_skip_reason()
    if reason is not None:
        print(json.dumps({'workload': 'composed_gpt2_pp_tp_fsdp_moe',
                          'mfu': None, 'note': f'skipped: {reason[:140]}'}))
        return
    from tpusystem.models import GPT2Pipelined
    from tpusystem.train import (NextTokenLoss, WithAuxLoss,
                                 build_train_step, flax_apply)
    mesh = MeshSpec(data=len(devices) // 8, fsdp=2, model=2,
                    stage=2).build(devices)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', pp='overlap',
                               moe='overlap', chunks=2, fsdp_min_size=4096)
    # layers/moe_every = 4 stacked spans must divide the stage axis (2);
    # pipeline_apply validates this at apply time
    module = GPT2Pipelined(vocab_size=50304, layers=16, dim=768, heads=12,
                           max_seq=1024, microbatches=8, mesh=mesh,
                           moe_experts=4, moe_every=4, schedule=schedule)
    batch = 16 * mesh.shape['data'] * mesh.shape['fsdp']
    tokens = jnp.asarray(rng.integers(0, 50257, (batch, 1024)), jnp.int32)
    optimizer = AdamW(lr=3e-4)
    state = init_state(module, optimizer, tokens[:1])
    state = PipelineParallel(
        stacked_rules=GPT2Pipelined.block_partition_rules(),
        fsdp=True).place(state, mesh)
    placed = jax.device_put(tokens, batch_sharding(mesh))
    step = build_train_step(flax_apply(module), WithAuxLoss(NextTokenLoss()),
                            optimizer, jit=False)

    single = jax.jit(lambda st: step(st, placed, placed)[0])
    flops = _flops(single.lower(state).compile())

    @partial(jax.jit, donate_argnums=0)
    def run(state):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, placed, placed)[0], state)

    state = run(state)
    float(jax.tree.leaves(state.params)[0].sum())
    start = time.perf_counter()
    state = run(state)
    float(jax.tree.leaves(state.params)[0].sum())
    elapsed = time.perf_counter() - start

    steps_per_sec = steps / elapsed
    peak = peak_flops(devices[0])
    result = {'workload': 'composed_gpt2_pp_tp_fsdp_moe',
              'mesh': {axis: size for axis, size in mesh.shape.items()
                       if size > 1},
              'steps_per_sec': round(steps_per_sec, 3),
              'flops_per_step': float(flops)}
    if peak:
        # per-chip MFU: executed FLOPs over every chip's peak
        result['mfu'] = round(flops * steps_per_sec
                              / (peak * len(devices)), 4)
    print(json.dumps(result))


rng = np.random.default_rng(0)

# ladder row 2: the tinysys-equivalent MNIST classifier (MLP 256/128)
images = jnp.asarray(rng.normal(size=(64, 28, 28)), jnp.float32)
labels = jnp.asarray(rng.integers(0, 10, (64,)), jnp.int32)
measure('classifier_mlp_bs64', MLP(features=(256, 128), classes=10),
        images, labels, steps=200)

# ladder row 3: ResNet-50 at 224^2, bf16 NHWC, bs 64
images = jnp.asarray(rng.normal(size=(64, 224, 224, 3)), jnp.bfloat16)
labels = jnp.asarray(rng.integers(0, 1000, (64,)), jnp.int32)
measure('resnet50_224_bs64', ResNet(), images, labels, steps=30)

# composed-mesh row: dp x fsdp x tp x stage, all four overlap arms on
# (the >= 0.60-MFU target row — skips cleanly off-pod)
composed_row()
