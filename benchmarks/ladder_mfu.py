"""MFU for workload-ladder rows 2 (classifier) and 3 (ResNet-50) — the
same unit as the ladder-4 headline (`bench.py`), same anti-hoisting
methodology (steps chained through the carried TrainState inside one jit,
completion forced by materializing a value).

FLOPs per step come from XLA's own cost model on the compiled single-step
program (`compile().cost_analysis()['flops']`): it counts the executed
fwd+bwd+optimizer HLO, so the number is an *executed*-FLOPs utilization —
marginally above a hand-counted model-FLOPs MFU (optimizer/elementwise
included), stated as such in BASELINE.md.
"""
import sys, time, json, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
from functools import partial

import jax, jax.numpy as jnp, numpy as np

from bench import peak_flops
from tpusystem.models import MLP, ResNet
from tpusystem.train import (AdamW, CrossEntropyLoss, build_train_step,
                             flax_apply, init_state)


def measure(tag, module, inputs, targets, steps):
    optimizer = AdamW(lr=1e-3)
    state = init_state(module, optimizer, inputs[:1])
    step = build_train_step(flax_apply(module), CrossEntropyLoss(),
                            optimizer, jit=False)

    single = jax.jit(lambda st: step(st, inputs, targets)[0])
    flops = single.lower(state).compile().cost_analysis().get('flops', 0.0)

    @partial(jax.jit, donate_argnums=0)
    def run(state):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, inputs, targets)[0], state)

    state = run(state)
    float(jax.tree.leaves(state.params)[0].sum())     # force completion
    start = time.perf_counter()
    state = run(state)
    float(jax.tree.leaves(state.params)[0].sum())
    elapsed = time.perf_counter() - start

    steps_per_sec = steps / elapsed
    peak = peak_flops(jax.devices()[0])
    result = {
        'workload': tag, 'steps_per_sec': round(steps_per_sec, 2),
        'flops_per_step': float(flops),
        'examples_per_sec': round(steps_per_sec * inputs.shape[0], 1),
    }
    if peak:
        result['mfu'] = round(flops * steps_per_sec / peak, 4)
    print(json.dumps(result))


rng = np.random.default_rng(0)

# ladder row 2: the tinysys-equivalent MNIST classifier (MLP 256/128)
images = jnp.asarray(rng.normal(size=(64, 28, 28)), jnp.float32)
labels = jnp.asarray(rng.integers(0, 10, (64,)), jnp.int32)
measure('classifier_mlp_bs64', MLP(features=(256, 128), classes=10),
        images, labels, steps=200)

# ladder row 3: ResNet-50 at 224^2, bf16 NHWC, bs 64
images = jnp.asarray(rng.normal(size=(64, 224, 224, 3)), jnp.bfloat16)
labels = jnp.asarray(rng.integers(0, 1000, (64,)), jnp.int32)
measure('resnet50_224_bs64', ResNet(), images, labels, steps=30)
