"""Disaggregated prefill/decode vs colocated serving: head-of-line TTFT.

The DistServe claim (``tpusystem/serve/disagg.py``) measured on a mixed
long:short workload — a few LONG prompts whose admission prefill is the
compute-bound phase, interleaved with many SHORT chat-style prompts.
Two fleets of the same replica count:

1. ``colocated`` — every replica serves both phases (``role='both'``):
   each long prefill runs on the same engine loop that co-batched
   decoders are waiting on, so short requests queued behind it eat the
   prefill's latency (head-of-line blocking);
2. ``disagg``   — one prefill-role replica admits every prompt and
   exports KV strips (``Engine.export_prefill``), the router ships them
   digest-verified over the blob plane (``kv:{request}``), and
   decode-role replicas seat them through ``admit_prefilled`` — decode
   steps never wait on a prefill.

Measured per arm: TTFT p50/p99 over the SHORT requests (the
head-of-line tail the split exists to fix), delivered tok/s, and
token-exactness — greedy decode is deterministic, so both arms must
produce identical completions (asserted every trial).

Every row is one machine-readable JSON line (the ``serve_fleet.py``
convention); the LAST line is the ``serve_disagg_ttft_p99`` headline
``bench.py`` forwards (value = disagg p99 short-request TTFT, colocated
alongside). CPU numbers are smoke; the TPU protocol rides the same
script (BASELINE.md "disaggregated serve protocol").

Run: ``python benchmarks/serve_disagg.py [headline]``.
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.parallel.multihost import Loopback
from tpusystem.serve import (Engine, ReplicaHandle, Request, Router,
                             Scheduler, ServingReplica)

TRIALS = 3
REPLICAS = 3                         # 1 prefill + 2 decode when split
ROWS = 2
ON_TPU = jax.default_backend() in ('tpu', 'axon')


def recipe():
    """Model + a long:short mixed workload: the long prompts are the
    head-of-line hazard (their prefill stalls a colocated engine loop),
    the short ones are the requests whose TTFT tail we report."""
    if ON_TPU:
        module = GPT2(dropout=0.0, vocab_size=50304, max_seq=1024)
        vocab, long_len, short_len = 50257, 384, 24
        longs, shorts, budget = 3, 12, 24
    else:
        module = gpt2_tiny(dtype='float32', layers=4, dim=256, heads=8,
                           vocab_size=1024, max_seq=256)
        vocab, long_len, short_len = 1024, 96, 8
        longs, shorts, budget = 2, 8, 10
    rng = np.random.default_rng(0)
    requests = []                    # (id, prompt, budget, is_short)
    for index in range(longs + shorts):
        short = index % (1 + shorts // max(longs, 1)) != 0 \
            if longs else True
        length = short_len if short else long_len
        prompt = rng.integers(0, vocab, (length,)).astype(np.int32).tolist()
        requests.append((f'r{index}', prompt, budget, short))
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray([requests[0][1][:8]],
                                     jnp.int32))['params']
    return module, params, requests


def build_fleet(module, params, *, split):
    """Same replica count both arms: ``split`` carves one replica into
    the prefill tier (its strips travel the Loopback blob plane), the
    colocated arm keeps every replica ``role='both'``."""
    wire = Loopback() if split else None
    handles = []
    for index in range(REPLICAS):
        role = ('prefill' if index == 0 else 'decode') if split else 'both'

        def build(role=role):
            return Scheduler(
                Engine(module, params, rows=ROWS,
                       block_size=16 if ON_TPU else 8),
                prefill_only=(role == 'prefill'))
        handles.append(ReplicaHandle(
            ServingReplica(build, identity=f'rep{index}', role=role),
            transport=wire, rank=0))
    return Router(handles), handles


def trial(module, params, requests, *, split, reference=None):
    """One drained run; returns (results, short TTFTs, elapsed).
    TTFT = submit -> the request's first emitted token crossing a
    FleetTick, the latency a caller actually observes."""
    router, _ = build_fleet(module, params, split=split)
    submitted, firsts = {}, {}
    started = time.perf_counter()
    for rid, prompt, budget, _short in requests:
        submitted[rid] = time.perf_counter()
        router.submit(Request(rid, list(prompt), budget))
    for _ in range(100_000):
        if router.idle:
            break
        tick = router.step()
        now = time.perf_counter()
        for rid in tick.emitted:
            firsts.setdefault(rid, now - submitted[rid])
    elapsed = time.perf_counter() - started
    assert router.idle, 'fleet never drained'
    if reference is not None:
        for rid, completion in router.results.items():
            expected = reference[rid].tokens
            assert completion.tokens == expected, (
                f'{rid} diverged across the disaggregation split: '
                f'{completion.tokens} vs {expected}')
    ttfts = [firsts[rid] for rid, _p, _b, short in requests if short]
    return router.results, sorted(ttfts), elapsed


def percentile(sorted_values, q):
    index = min(len(sorted_values) - 1,
                int(round(q * (len(sorted_values) - 1))))
    return sorted_values[index]


def main() -> None:
    module, params, requests = recipe()
    tokens_out = sum(budget for _rid, _p, budget, _s in requests)
    workload = (f'{len(requests)} reqs '
                f'({sum(1 for r in requests if not r[3])} long / '
                f'{sum(1 for r in requests if r[3])} short) over '
                f'{REPLICAS} replicas')

    colo_p99s, colo_p50s, colo_toks = [], [], []
    disagg_p99s, disagg_p50s, disagg_toks = [], [], []
    reference = None
    for _ in range(TRIALS):
        results, ttfts, elapsed = trial(module, params, requests,
                                        split=False, reference=reference)
        reference = reference or results
        colo_p50s.append(percentile(ttfts, 0.50))
        colo_p99s.append(percentile(ttfts, 0.99))
        colo_toks.append(tokens_out / elapsed)
        _results, ttfts, elapsed = trial(module, params, requests,
                                         split=True, reference=reference)
        disagg_p50s.append(percentile(ttfts, 0.50))
        disagg_p99s.append(percentile(ttfts, 0.99))
        disagg_toks.append(tokens_out / elapsed)

    median = lambda values: sorted(values)[len(values) // 2]
    print(json.dumps({
        'metric': 'serve_colocated_ttft_p99',
        'value': round(median(colo_p99s), 4),
        'unit': 's submit -> first token, short requests (colocated: '
                'long prefills share the decode loop)',
        'p50': round(median(colo_p50s), 4),
        'tok_s': round(median(colo_toks), 2)}))
    print(json.dumps({
        'metric': 'serve_disagg_ttft_p99',
        'value': round(median(disagg_p99s), 4),
        'unit': f's submit -> first token, short requests ({workload}; '
                'prefill tier + KV handoff over the blob plane, '
                'token-exact vs colocated)'
                + ('' if ON_TPU else ' [CPU smoke]'),
        'p50': round(median(disagg_p50s), 4),
        'tok_s': round(median(disagg_toks), 2),
        'colocated_p99': round(median(colo_p99s), 4),
        'colocated_p50': round(median(colo_p50s), 4),
        'colocated_tok_s': round(median(colo_toks), 2),
    }))


if __name__ == '__main__':
    main()        # 'headline' arg tolerated: every section prints anyway
