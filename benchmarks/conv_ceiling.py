"""Convolution ceiling: what MFU can ResNet-50's conv shapes reach at all?

Times every distinct convolution in ResNet-50 (bf16 NHWC, fwd only, the
MXU-friendly layout) in isolation, plus an equal-FLOPs square matmul as
the platform's best case. The FLOPs-weighted composite of the per-shape
rates is the convolution ceiling for the whole network: if the train-step
MFU (ladder row 3) sits near the composite, the gap to the transformer
headline is the platform's conv lowering, not the training recipe.

Run: ``python benchmarks/conv_ceiling.py [batch]``
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bench import peak_flops

# (spatial, cin, cout, kernel, stride, count) — every conv in ResNet-50
# (stem + 4 stages of bottlenecks with their 1x1/3x3/1x1 + projections)
RESNET50_CONVS = [
    (224, 3, 64, 7, 2, 1),      # stem
    (56, 64, 64, 1, 1, 3),      # stage1 1x1 in
    (56, 64, 64, 3, 1, 3),      # stage1 3x3
    (56, 64, 256, 1, 1, 4),     # stage1 1x1 out + proj
    (56, 256, 64, 1, 1, 2),     # stage1 1x1 in (later blocks)
    (56, 256, 128, 1, 2, 2),    # stage2 in + proj (strided)
    (28, 128, 128, 3, 1, 4),    # stage2 3x3 (first is stride-2 from 56)
    (28, 128, 512, 1, 1, 4),
    (28, 512, 128, 1, 1, 3),
    (28, 512, 256, 1, 2, 2),    # stage3 in + proj
    (14, 256, 256, 3, 1, 6),
    (14, 256, 1024, 1, 1, 6),
    (14, 1024, 256, 1, 1, 5),
    (14, 1024, 512, 1, 2, 2),   # stage4 in + proj
    (7, 512, 512, 3, 1, 3),
    (7, 512, 2048, 1, 1, 3),
    (7, 2048, 512, 1, 1, 2),
]
REPEATS = 1000


def time_op(fn, x, w) -> float:
    """Mean seconds per op over REPEATS data-DEPENDENT calls inside one
    ``fori_loop``: each iteration folds a scalar of the op's output back
    into the carried input (times 1e-7, not 0 — XLA folds multiplications
    by zero; a data dependency defeats CSE/hoisting), so every iteration
    really runs the op. The chain adds one x-sized broadcast-add per rep
    — the realistic inter-op condition inside a residual network. 1000
    reps keep the ~15 ms per-dispatch relay overhead under 1% even for
    the smallest conv."""
    def body(_, carry):
        y = fn(carry, w)
        feedback = y[(0,) * y.ndim].astype(carry.dtype)
        return carry + feedback * jnp.asarray(1e-7, carry.dtype)
    run = jax.jit(lambda x, w: jax.lax.fori_loop(0, REPEATS, body, x))
    out = run(x, w)
    float(jnp.sum(out.astype(jnp.float32)))
    t0 = time.perf_counter()
    out = run(x, w)
    float(jnp.sum(out.astype(jnp.float32)))
    return (time.perf_counter() - t0) / REPEATS


def main(batch: int) -> None:
    peak = peak_flops(jax.devices()[0])
    rng = np.random.default_rng(0)
    total_flops, total_time = 0.0, 0.0
    for spatial, cin, cout, k, stride, count in RESNET50_CONVS:
        x = jnp.asarray(rng.normal(size=(batch, spatial, spatial, cin)),
                        jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(k, k, cin, cout)), jnp.bfloat16)
        conv = partial(jax.lax.conv_general_dilated,
                       window_strides=(stride, stride), padding='SAME',
                       dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
        seconds = time_op(conv, x, w)
        out_sp = spatial // stride
        flops = 2 * batch * out_sp * out_sp * k * k * cin * cout
        rate = flops / seconds
        total_flops += flops * count
        total_time += seconds * count
        print(json.dumps({
            'conv': f'{spatial}x{spatial} {cin}->{cout} k{k} s{stride}',
            'count': count, 'gflops': round(flops / 1e9, 2),
            'mfu': round(rate / peak, 3)}))

    composite = total_flops / total_time / peak
    # equal-FLOPs best case: one square bf16 matmul sized to the average
    # per-conv FLOPs (the MXU rate the platform gives dense contraction)
    n = 4096
    a = jnp.asarray(rng.normal(size=(n, n)), jnp.bfloat16)
    b = jnp.asarray(rng.normal(size=(n, n)), jnp.bfloat16)
    mm = time_op(lambda a, b: jnp.dot(a, b, preferred_element_type=jnp.float32)
                 .astype(jnp.bfloat16), a, b)  # same chained harness
    mm_mfu = 2 * n ** 3 / mm / peak
    print(json.dumps({
        'composite_conv_mfu_fwd': round(composite, 4),
        'matmul_4096_mfu': round(mm_mfu, 4),
        'batch': batch,
        'note': 'composite = FLOPs-weighted fwd conv ceiling over all '
                'ResNet-50 shapes; train-step MFU also pays backward '
                '(input+filter grads, ~2x fwd at similar shapes), '
                'normalization + optimizer',
    }))


if __name__ == '__main__':
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 64)
