"""Whole-model 8B rehearsal: a MEASURED step, not a component composite.

Replaces the round-3 methodology for BASELINE row 5 (one block + head timed
separately, composite modeled over 32 layers) with two whole-model runs:

* ``chip`` (default): the deepest Llama-8B-dim stack that fits one 16 GB
  chip — dim 4096, GQA 32/8, SwiGLU 14336, seq 8192, remat + flash +
  fused chunked loss + AdamW — fwd+bwd+update timed end-to-end over
  repeated dispatches (at ~0.5 s/step the ~7 ms relay dispatch is <2%,
  so no steps-loop is needed — which also keeps the scanned stack clear
  of the relay compiler's nested-loop cliff, see scan_compile_probe.py).
  The vocab shrinks to 16384 (x128) so the untied head + embedding fit
  next to the blocks (32768 overflows HBM by ~100 MB at 4 layers); FLOPs
  are counted from the actual parameter count, so MFU is honest for the
  measured program.

* ``virtual``: the full composition rehearsal on an 8-device CPU mesh —
  scan+TP+FSDP+flash at dim 4096, >=8 layers — recording AOT compile
  time and the per-layer collective count from the optimized HLO (the
  number that predicts ICI time on a pod).

Run: ``python benchmarks/llama8b_rehearsal.py [chip|virtual] [layers=N]``
"""

from __future__ import annotations

import sys
sys.path.insert(0, str(__import__('pathlib').Path(__file__).parent.parent))

import json
import re
import time

import jax
import jax.numpy as jnp
import numpy as np


def build(layers: int, vocab: int, mesh=None, scan: bool = False,
          attention: str = 'flash', ffn: int = 14336):
    from tpusystem.models import Llama
    return Llama(vocab_size=vocab, layers=layers, dim=4096, heads=32,
                 kv_heads=8, ffn_dim=ffn, max_seq=8192,
                 attention=attention, mesh=mesh, remat=True,
                 scan_layers=scan, scan_unit=4 if scan and layers % 4 == 0
                 else 1, return_features=True)


def chip(layers: int, scan: bool = False) -> None:
    from bench import peak_flops
    from tpusystem.train import (AdamW, ChunkedNextTokenLoss,
                                 build_train_step, flax_apply, init_state)

    batch, seq, vocab = 1, 8192, 16384  # 32768 exceeds the
    # 16 GB chip by ~100 MB next to 4 blocks; FLOPs count actual params
    module = build(layers, vocab, scan=scan)
    optimizer = AdamW(lr=3e-4, grad_clip=1.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, vocab, (batch, seq)), jnp.int32)
    state = init_state(module, optimizer, tokens[:1, :8])
    params = sum(leaf.size for leaf in jax.tree.leaves(state.params))
    step = build_train_step(flax_apply(module),
                            ChunkedNextTokenLoss(chunks=8, tied=False),
                            optimizer)

    t0 = time.perf_counter()
    state, (_, loss) = step(state, tokens, tokens)
    float(loss)
    compile_s = time.perf_counter() - t0

    repeats = 10
    t0 = time.perf_counter()
    for _ in range(repeats):
        state, (_, loss) = step(state, tokens, tokens)
    float(loss)
    elapsed = (time.perf_counter() - t0) / repeats

    head_dim = 4096 // 32
    attention_flops = 12 * layers * 32 * seq * seq * head_dim * batch
    step_flops = 6 * params * batch * seq + attention_flops
    mfu = step_flops / elapsed / peak_flops(jax.devices()[0])
    print(json.dumps({
        'mode': 'chip', 'layers': layers, 'scan': scan, 'params': params,
        'seq': seq, 'compile_s': round(compile_s, 1),
        'ms_per_step': round(elapsed * 1e3, 1), 'mfu': round(mfu, 4),
        'tok_per_s': round(batch * seq / elapsed),
    }))


def virtual(layers: int, ffn: int = 14336, execute: bool = True) -> None:
    import os
    os.environ.setdefault('XLA_FLAGS',
                          '--xla_force_host_platform_device_count=8')
    jax.config.update('jax_platforms', 'cpu')
    # O0 like the driver dryrun: the default pipeline's large fused thunks
    # starve XLA:CPU's shared-pool collective rendezvous (40 s timeout) at
    # these matmul sizes; the sharding/collective structure is unchanged
    jax.config.update('jax_optimization_level', 'O0')
    from tpusystem.parallel import MeshSpec, TensorParallel, batch_sharding
    from tpusystem.train import (ChunkedNextTokenLoss, SGD, build_train_step,
                                 flax_apply, init_state)

    # seq kept small: XLA:CPU runs all 8 virtual devices on one shared
    # thread pool, and matmuls much larger than this starve collective
    # participants past the backend's fixed 40 s rendezvous timeout
    # (rendezvous.cc termination) — the sharding/collective structure
    # being validated is seq-independent
    batch, seq, vocab = 4, 128, 16384
    mesh = MeshSpec(data=2, fsdp=2, model=2).build(jax.devices('cpu')[:8])
    module = build(layers, vocab, mesh=mesh, scan=True, ffn=ffn)
    # SGD + bf16 params: the rehearsal validates sharding/collectives and
    # compile time at real dims on host memory, not optimizer math
    optimizer = SGD(lr=1e-3)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, vocab, (batch, seq)), jnp.int32)
    print('phase: init_state', flush=True)
    t0 = time.perf_counter()
    # eval_shape + zeros instead of init_state: actually sampling 1.75B
    # params eagerly on the CPU backend takes >15 minutes; the rehearsal
    # validates the compiled program's sharding/collective structure,
    # which is value-independent (zero weights still give a finite
    # log-uniform loss and execute every collective)
    from tpusystem.train.state import TrainState
    shapes = jax.eval_shape(module.init, jax.random.PRNGKey(0),
                            tokens[:1, :8])
    zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, jnp.bfloat16),
                         shapes['params'])
    transform = optimizer.transform()
    state = TrainState.create(zeros, transform.init(zeros),
                              jax.random.PRNGKey(1))
    params = sum(leaf.size for leaf in jax.tree.leaves(state.params))
    print('phase: place', flush=True)
    state = TensorParallel(module.partition_rules(), fsdp=True).place(
        state, mesh)
    init_s = time.perf_counter() - t0
    placed = jax.device_put(tokens, batch_sharding(mesh))
    step = build_train_step(flax_apply(module),
                            ChunkedNextTokenLoss(chunks=4, tied=False),
                            optimizer, jit=False)

    jitted = jax.jit(step, donate_argnums=0)
    print('phase: lower', flush=True)
    t0 = time.perf_counter()
    lowered = jitted.lower(state, placed, placed)
    lower_s = time.perf_counter() - t0
    print('phase: compile', flush=True)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0

    hlo = compiled.as_text()
    collectives = {
        kind: len(re.findall(rf'\b{kind}[-.\w]*\(', hlo))
        for kind in ('all-reduce', 'all-gather', 'reduce-scatter',
                     'all-to-all', 'collective-permute')}
    print(json.dumps({
        'mode': 'virtual', 'layers': layers, 'ffn': ffn, 'params': params,
        'mesh': {'data': 2, 'fsdp': 2, 'model': 2},
        'init_s': round(init_s, 1), 'lower_s': round(lower_s, 1),
        'compile_s': round(compile_s, 1),
        'collectives_total': collectives,
        'collectives_per_layer': {k: round(v / layers, 2)
                                  for k, v in collectives.items()},
    }), flush=True)
    if not execute:
        # full-ffn leg records the compile + collective structure only:
        # XLA:CPU's in-process collectives carry a hard 40 s rendezvous
        # timeout that GB-scale per-device matmul work overruns (the
        # collective COUNT — the pod-relevant number — is ffn-independent)
        return
    t0 = time.perf_counter()
    state, (_, loss) = compiled(state, placed, placed)
    loss = float(loss)
    exec_s = time.perf_counter() - t0
    assert np.isfinite(loss), loss
    print(json.dumps({'mode': 'virtual-exec', 'ffn': ffn,
                      'exec_s': round(exec_s, 1), 'loss': round(loss, 4)}))


if __name__ == '__main__':
    layers = next((int(a.split('=')[1]) for a in sys.argv[1:]
                   if a.startswith('layers=')), None)
    if 'virtual' in sys.argv[1:]:
        # leg 1: full 8B ffn — compile + per-layer collective count;
        # leg 2: ffn shrunk 14336 -> 4096 — same collective structure,
        # light enough for XLA:CPU to execute inside its rendezvous window
        virtual(layers or 8, execute=False)
        virtual(layers or 8, ffn=4096)
    else:
        chip(layers or 4, scan='scan' in sys.argv[1:])
